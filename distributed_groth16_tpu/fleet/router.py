"""The fleet front door: one router process over N replica ApiServers.

zkSaaS scales one star (a king + n-1 clients); "heavy traffic from
millions of users" needs many stars behind one door. The router is that
door (docs/FLEET.md): a thin aiohttp process that owns NO proving code —
it admits, schedules, dispatches, proxies, and hands off. Each replica
is a full PR 7 crash-safe ApiServer with its own device inventory and
durable journal; all replicas share the circuit store.

Request path for `POST /jobs/prove` (and its verification-plane siblings
`POST /jobs/verify` / `POST /jobs/aggregate` — same admission, same
weighted-fair queue, dispatched to the matching replica endpoint;
docs/VERIFY.md):

  1. tenant identity from the `X-DG16-Tenant` header (absent ->
     "anonymous") and a priority class from `X-DG16-Priority` /
     the `priority` multipart field (interactive | batch | bulk);
  2. admission: the tenant's token bucket + in-flight quota
     (fleet/tenants.py) and the router's dispatch-backlog bound — any
     failure is HTTP 429 whose retryAfter is the MAX over the tenant
     bucket's refill hint and the replicas' own last 429 hints;
  3. the job enters the weighted-fair dispatch queue and the response
     returns immediately (202, state PENDING) — same contract as a
     replica's jobs API, one hop earlier;
  4. the dispatcher pops fairly (tenants round-robin inside classes,
     classes by weight) and POSTs to the least-loaded live replica
     (registry score: load x (1 + SLO burn)), carrying a router-minted
     `job_id` so any re-submission is idempotent;
  5. status/result/trace/cancel proxy through the router by job id —
     clients never need to know which replica proved their job.

Journal-backed handoff: when a replica is EJECTED (stopped answering /
kept 5xx-ing) or begins DRAINING, the router reads its journal directory
(shared filesystem — `DG16_FLEET_REPLICAS=url=journal-dir`) off the event
loop and re-submits every replayable job to a healthy replica under the
SAME job id. If the "dead" replica was merely slow and replays its own
journal too, both sides converge: submission is idempotent by job id on
every replica and in every journal, so the job proves at most once per
replica and the client sees one terminal state. Nothing accepted is lost.

Fleet observatory (docs/OBSERVABILITY.md): the router mints a `trace_id`
next to the job id and propagates it in `X-DG16-Trace`; every hop the
job takes at the front door is a router-side span, and
`GET /fleet/jobs/{id}/trace` stitches them with the replica's merged job
trace (ClockSync-rebased from /readyz poll echoes) into ONE Chrome
trace. The discovery loop also scrapes each replica's `/metrics` and
`GET /fleet/metrics` federates them (fleet/federate.py); an anomaly hook
flight-dumps replicas whose p95/burn deviates from the fleet median.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import statistics
import time
import uuid
from dataclasses import dataclass, field
from collections import deque

import aiohttp
from aiohttp import web

from ..service.journal import read_journal
from ..telemetry import flight as _flight
from ..telemetry import logbus as _logbus
from ..telemetry import metrics as _tm
from ..telemetry.aggregate import ClockSync, now_ns as _now_ns
from ..utils.config import FleetConfig, TenantConfig
from .federate import MetricsFederator
from .registry import ACTIVE, DRAINING, EJECTED, Replica, ReplicaRegistry
from .tenants import (
    DEFAULT_PRIORITY,
    DEFAULT_TENANT,
    TenantAdmission,
    TenantQuotaError,
    WeightedFairQueue,
)

log = logging.getLogger(__name__)

MAX_BODY = 100 * 1024 * 1024  # mirror the replica body cap

_TERMINAL = ("DONE", "FAILED", "CANCELLED")

# the router's track id in stitched fleet traces: far above any MPC party
# pid (a replica trace uses 0..n-1), so the three tiers never collide
ROUTER_PID = 9999

# per-job router span cap: a job normally records a handful (admission,
# one queue wait, one dispatch); a pathological requeue loop must not
# grow an unbounded event list on a retained job
MAX_JOB_SPANS = 256

_REG = _tm.registry()
_ROUTED = _REG.counter(
    "fleet_jobs_routed_total",
    "Jobs dispatched to a replica, per tenant and priority class",
    ("tenant", "priority"),
)
_HANDOFFS = _REG.counter(
    "fleet_handoffs_total",
    "Journaled jobs re-submitted to a healthy replica after their "
    "owner died (death) or began draining (drain)",
    ("reason",),
)
_HTTP_SECONDS = _REG.histogram(
    "fleet_http_seconds",
    "Router front-door HTTP latency per route and status code — "
    "measured in middleware, so front-door cost is separable from "
    "replica latency",
    ("route", "code"),
)
_PROXY_ERRORS = _REG.counter(
    "fleet_proxy_errors_total",
    "Proxied replica requests that failed at the router (unreachable "
    "replica, bad body), per route",
    ("route",),
)
_ANOMALIES = _REG.counter(
    "fleet_anomalies_total",
    "Fleet-anomaly episodes: a replica's p95 or burn rate exceeded the "
    "fleet median by DG16_FLEET_ANOMALY_FACTOR (each also writes a "
    "flight-recorder dump, trigger fleet_anomaly)",
    ("replica", "signal"),
)


def _error(msg: str, status: int = 500) -> web.Response:
    return web.json_response({"error": msg}, status=status)


def _busy(tenant: str, reason: str, retry_after_s: float,
          detail: str) -> web.Response:
    return web.json_response(
        {
            "error": detail,
            "tenant": tenant,
            "reason": reason,
            "retryAfter": round(retry_after_s, 1),
        },
        status=429,
        headers={"Retry-After": str(int(retry_after_s) or 1)},
    )


async def _read_multipart(request) -> dict[str, bytes]:
    # deliberately NOT imported from api.server: the router owns no
    # proving code, so it must not depend on the prover-facing module
    reader = await request.multipart()
    out = {}
    async for part in reader:
        out[part.name] = await part.read(decode=False)
    return out


@dataclass
class RoutedJob:
    """One job as the router tracks it: identity + placement, never the
    payload once dispatched (the replica's journal is the durable copy;
    holding every payload in router memory would cap the fleet at the
    router's RAM)."""

    id: str
    tenant: str
    priority: str
    circuit_id: str
    kind: str
    # end-to-end trace id, minted next to the job id and propagated to
    # the replica in X-DG16-Trace (docs/OBSERVABILITY.md)
    trace_id: str = ""
    state: str = "PENDING"
    replica: Replica | None = None
    created_at: float = field(default_factory=time.time)
    attempts: int = 0
    charged: bool = True  # holds a tenant in-flight slot until terminal
    cancelled: bool = False  # DELETE before dispatch: dispatcher skips
    error: dict | None = None  # router-side terminal failure, if any
    # router-side spans of this job's life at the front door (Chrome
    # trace-event dicts on the router's perf_counter clock): admission,
    # each queue wait, each dispatch attempt, handoff — the router tier
    # of the stitched GET /fleet/jobs/{id}/trace
    spans: list = field(default_factory=list, repr=False)
    queued_pc: float = 0.0  # perf_counter at the last enqueue

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    def record_span(self, name: str, t0: float, dur: float, **attrs) -> None:
        if len(self.spans) >= MAX_JOB_SPANS:
            return
        self.spans.append(
            {
                "name": name,
                "ph": "X",
                "ts": round(t0 * 1e6, 1),
                "dur": round(dur * 1e6, 1),
                "pid": ROUTER_PID,
                "tid": 0,
                "args": attrs,
            }
        )

    def to_dict(self) -> dict:
        out = {
            "jobId": self.id,
            "tenant": self.tenant,
            "priority": self.priority,
            "traceId": self.trace_id,
            "circuitId": self.circuit_id,
            "state": self.state,
            "replica": self.replica.name if self.replica else None,
            "createdAt": self.created_at,
        }
        if self.error is not None:
            out["error"] = self.error
        return out


class FleetRouter:
    def __init__(
        self,
        cfg: FleetConfig | None = None,
        tenant_cfg: TenantConfig | None = None,
    ):
        self.cfg = cfg or FleetConfig.from_env()
        # logging spine: ring handler on (idempotent — an in-process
        # test fleet shares one ring with its replicas; the /fleet logs
        # route filters to fleet-tier loggers to stay distinct)
        _logbus.setup(console=False)
        self.registry = ReplicaRegistry(
            self.cfg.replicas,
            eject_threshold=self.cfg.eject_threshold,
            eject_cooldown_s=self.cfg.eject_cooldown_s,
        )
        self.admission = TenantAdmission(tenant_cfg or TenantConfig.from_env())
        self.queue = WeightedFairQueue(self.cfg.weights)
        self.federator = MetricsFederator()
        # (replica, signal) pairs currently over the anomaly threshold —
        # one flight dump per episode, re-armed on recovery
        self._anomaly_latched: set[tuple[str, str]] = set()
        self.jobs: dict[str, RoutedJob] = {}
        self._payloads: dict[str, dict[str, bytes]] = {}  # pending only
        self._terminal_order: deque[str] = deque()
        self.draining = False
        self.handoffs = 0
        self._last_replica_hint = 0.0  # newest replica-side 429 retryAfter
        self._hint_at = 0.0  # when it arrived (monotonic)
        self._wake: asyncio.Event | None = None
        self._tasks: list[asyncio.Task] = []
        self._session: aiohttp.ClientSession | None = None

    # -- lifecycle ------------------------------------------------------------

    async def _on_startup(self, app) -> None:
        # force_close: a pooled keepalive socket to a dead replica hides
        # the death until a write fails mid-request — a router must learn
        # about replica loss at connect time, not from a torn response
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(force_close=True)
        )
        self._wake = asyncio.Event()
        self._tasks = [
            asyncio.create_task(self._discovery_loop(), name="fleet-poll"),
            asyncio.create_task(self._dispatch_loop(), name="fleet-dispatch"),
        ]

    async def _on_cleanup(self, app) -> None:
        self.draining = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- discovery ------------------------------------------------------------

    async def _discovery_loop(self) -> None:
        while True:
            try:
                await self.poll_once()
                self.federator.retain(
                    {
                        r.name
                        for r in self.registry.replicas
                        if r.state != EJECTED
                    }
                )
                self.federator.tick()
                self._anomaly_pass()
                await self._handoff_pass()
                await self._sweep_jobs()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — discovery must not die
                log.exception("fleet discovery pass failed")
            await asyncio.sleep(self.cfg.poll_s)

    async def poll_once(self) -> None:
        """One discovery tick: GET every pollable replica's /readyz."""
        await asyncio.gather(
            *(self._poll_replica(r) for r in self.registry.pollable())
        )

    def _note_replica_failure(self, replica: Replica, op: str) -> None:
        """Feed the ejection breaker; an ejection is a fleet-tier fault
        the flight recorder must witness (docs/OBSERVABILITY.md)."""
        if self.registry.note_failure(replica):
            log.warning("replica %s ejected (%s)", replica.name, op)
            _flight.note(
                "replica_ejected", replica=replica.name, op=op
            )
            _flight.dump_soon(
                "replica_ejected",
                extra={"replica": replica.name, "op": op},
            )

    async def _poll_replica(self, replica: Replica) -> None:
        # the poll doubles as a clock-echo round (NTP-style, the PR 4
        # heartbeat shape): t0/t3 on the router's perf_counter_ns, t1/t2
        # echoed by the replica — feeding the per-replica ClockSync that
        # rebases its trace events in the stitched fleet trace
        t0 = _now_ns()
        try:
            async with self._session.get(
                f"{replica.url}/readyz",
                params={"echo": str(t0)},
                timeout=aiohttp.ClientTimeout(total=max(1.0, self.cfg.poll_s)),
            ) as resp:
                doc = await resp.json()
            t3 = _now_ns()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as e:
            log.debug("poll %s failed: %r", replica.name, e)
            self._note_replica_failure(replica, "poll")
            return
        # 503 + draining body is an ANSWER (deliberate drain), any other
        # non-200 is a failure
        if resp.status == 200 or doc.get("draining"):
            echo = doc.get("clockEcho") or {}
            try:
                t1, t2 = int(echo["t1"]), int(echo["t2"])
            except (KeyError, TypeError, ValueError):
                pass  # pre-echo replica: stitching falls back to offset 0
            else:
                replica.clock.add_sample(*ClockSync.from_echo(t0, t1, t2, t3))
            self.registry.note_doc(replica, doc)
            if self._wake is not None:
                # capacity may have appeared — wake the dispatcher
                # BEFORE the federation scrape, so a slow /metrics
                # cannot delay queued jobs that already have a home
                self._wake.set()
            await self._scrape_replica(replica)
        else:
            self._note_replica_failure(replica, "poll")

    async def _scrape_replica(self, replica: Replica) -> None:
        """Federation scrape, same tick as the capacity poll. A failed
        scrape never feeds the ejection breaker — /readyz just answered,
        so the replica is alive; only the fleet view goes stale."""
        try:
            async with self._session.get(
                f"{replica.url}/metrics",
                timeout=aiohttp.ClientTimeout(total=max(1.0, self.cfg.poll_s)),
            ) as resp:
                if resp.status != 200:
                    self.federator.note_failure(replica.name)
                    return
                text = await resp.text()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            self.federator.note_failure(replica.name)
            return
        self.federator.note_scrape(replica.name, text)

    # -- fleet anomaly hook ----------------------------------------------------

    def _anomaly_pass(self) -> None:
        """Flag replicas whose federated p95 or SLO burn deviates from
        the fleet median beyond the knob'd factor: one counter increment
        and one flight-recorder post-mortem per episode (latched until
        the signal recovers). Needs >= 3 replicas with data — a median
        of two is just the other replica."""
        factor = self.cfg.anomaly_factor
        if factor <= 0:
            return
        self._check_anomaly("p95_seconds", self.federator.replica_p95(), factor)
        self._check_anomaly("burn_rate", self.federator.replica_burn(), factor)

    def _check_anomaly(self, signal: str, values: dict, factor: float) -> None:
        # a replica that stopped reporting this signal (ejected, or a
        # restart reset it below the sample floor) re-arms: its next
        # episode after rejoining must dump again, not hit a stale latch
        self._anomaly_latched -= {
            (name, sig)
            for name, sig in self._anomaly_latched
            if sig == signal and name not in values
        }
        if len(values) < 3:
            return
        median = statistics.median(values.values())
        if median <= 0:
            return
        for name, value in values.items():
            key = (name, signal)
            if value > median * factor:
                if key in self._anomaly_latched:
                    continue
                self._anomaly_latched.add(key)
                _ANOMALIES.labels(replica=name, signal=signal).inc()
                log.warning(
                    "fleet anomaly: replica %s %s=%.3f vs fleet median %.3f",
                    name, signal, value, median,
                )
                _flight.note(
                    "fleet_anomaly", replica=name, signal=signal,
                    value=value, median=median,
                )
                _flight.dump_soon(
                    "fleet_anomaly",
                    extra={
                        "replica": name,
                        "signal": signal,
                        "value": value,
                        "fleetMedian": median,
                        "factor": factor,
                    },
                )
            else:
                self._anomaly_latched.discard(key)

    # -- handoff --------------------------------------------------------------

    async def _handoff_pass(self) -> None:
        for replica in self.registry.needs_handoff():
            await self._handoff(replica)

    async def _handoff(self, replica: Replica) -> int:
        """Re-route a dead/draining replica's journaled backlog. Latches
        per outage (handoff_done) so one death costs one journal read."""
        reason = "death" if replica.state == EJECTED else "drain"
        if not replica.journal_dir:
            replica.handoff_done = True
            log.warning(
                "replica %s needs handoff but has no journal dir configured "
                "— its accepted jobs must wait for its own restart replay",
                replica.name,
            )
            return 0
        # journal parse decodes every live payload — never on the loop.
        # The latch is only set AFTER the read succeeds: a transient
        # read error (shared-journal mount hiccup) must leave the
        # handoff retryable on the next discovery pass, not strand the
        # dead replica's accepted jobs forever.
        t_read0 = time.perf_counter()
        entries = await asyncio.to_thread(read_journal, replica.journal_dir)
        replica.handoff_done = True
        moved = 0
        for e in entries:
            if not e.replayable:
                continue
            known = self.jobs.get(e.id)
            if known is not None:
                if known.terminal or known.state == "PENDING":
                    continue  # finished, or already re-queued for dispatch
                if known.replica is not None and known.replica is not replica:
                    # a PREVIOUS handoff already moved it to a healthy
                    # replica (the dead one's journal still lists it
                    # live) — re-queueing would run the proof again and
                    # regress the client-visible state to PENDING
                    continue
            job = known or RoutedJob(
                id=e.id,
                tenant=e.tenant or DEFAULT_TENANT,
                priority=e.priority or DEFAULT_PRIORITY,
                circuit_id=e.circuit_id,
                kind=e.kind,
                # the journaled trace id survives the handoff: the
                # re-proved job stitches into the SAME end-to-end trace
                trace_id=e.trace_id or uuid.uuid4().hex,
                created_at=e.created_at,
                # jobs the router never admitted (posted straight to the
                # replica) are grandfathered: no tenant slot to release
                charged=False,
            )
            job.state = "PENDING"
            job.replica = None
            self.jobs[job.id] = job
            job.record_span(
                "fleet.handoff",
                t_read0,
                time.perf_counter() - t_read0,
                source=replica.name,
                reason=reason,
            )
            # rebuild the full submission: the journal keeps the payload
            # fields (witness/input bytes) and the rest of the identity
            # as record columns. The re-queued payloads live in router
            # memory until re-dispatched — bounded by the dead replica's
            # own admission bound (its journal can hold at most one
            # queue's worth of live jobs), and deliberately exempt from
            # pending_bound: these jobs were already accepted once.
            fields = dict(e.fields)
            fields["circuit_id"] = e.circuit_id.encode()
            fields["l"] = str(e.l).encode()
            if e.kind == "mpc_prove":
                fields["mpc"] = b"1"
            self._payloads[job.id] = fields
            self._queue_job(job)
            _HANDOFFS.labels(reason=reason).inc()
            self.handoffs += 1
            moved += 1
        if moved:
            log.info(
                "handoff: re-queued %d journaled job(s) from %s (%s)",
                moved, replica.name, reason,
            )
            _flight.note(
                "fleet_handoff", replica=replica.name, reason=reason,
                moved=moved,
            )
            _flight.dump_soon(
                "fleet_handoff",
                extra={
                    "replica": replica.name,
                    "reason": reason,
                    "moved": moved,
                },
            )
            if self._wake is not None:
                self._wake.set()
        return moved

    # -- job-state sweep ------------------------------------------------------

    async def _sweep_jobs(self) -> None:
        """Refresh non-terminal dispatched jobs from their replicas and
        release tenant in-flight slots as they finish — the quota must
        not depend on clients polling through the router."""
        live = [
            j for j in self.jobs.values()
            # EJECTED owners are unreachable; handoff owns those jobs
            if j.replica is not None and not j.terminal
            and j.replica.state != EJECTED
        ]
        # concurrent like poll_once: a sweep must cost one timeout, not
        # one per job, or a slow replica stalls ejection and handoff
        await asyncio.gather(*(self._sweep_one(j) for j in live))

    async def _sweep_one(self, job: RoutedJob) -> None:
        try:
            async with self._session.get(
                f"{job.replica.url}/jobs/{job.id}",
                timeout=aiohttp.ClientTimeout(total=max(1.0, self.cfg.poll_s)),
            ) as resp:
                if resp.status != 200:
                    return
                body = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        self._note_state(job, body.get("state", job.state))

    def _note_state(self, job: RoutedJob, state: str) -> None:
        if job.terminal:
            return
        job.state = state
        if job.terminal:
            self._payloads.pop(job.id, None)
            if job.charged:
                self.admission.release(job.tenant)
                job.charged = False
            self._note_terminal(job)

    def _note_terminal(self, job: RoutedJob) -> None:
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.cfg.history:
            jid = self._terminal_order.popleft()
            j = self.jobs.get(jid)
            if j is not None and j.terminal:
                del self.jobs[jid]

    # -- dispatch -------------------------------------------------------------

    def _queue_job(self, job: RoutedJob) -> None:
        """Every enqueue goes through here so the queue-wait span always
        has its start stamp."""
        job.queued_pc = time.perf_counter()
        self.queue.push(job.tenant, job.priority, job)

    async def _dispatch_loop(self) -> None:
        while True:
            job = self.queue.pop()
            if job is None:
                await self._wait_for_work()
                continue
            if job.queued_pc:
                now = time.perf_counter()
                job.record_span(
                    "fleet.queue", job.queued_pc, now - job.queued_pc,
                    priority=job.priority,
                )
                job.queued_pc = 0.0
            if job.cancelled:
                self._note_state(job, "CANCELLED")
                continue
            ok = await self._dispatch(job)
            if not ok:
                # no replica could take it right now: back of its own
                # tenant line, then wait for capacity (a poll refreshes
                # scores and sets the wake event)
                self._queue_job(job)
                await self._wait_for_work()

    async def _wait_for_work(self) -> None:
        try:
            await asyncio.wait_for(self._wake.wait(), self.cfg.poll_s)
        except asyncio.TimeoutError:
            pass
        self._wake.clear()

    async def _dispatch(self, job: RoutedJob) -> bool:
        """Try every active replica best-first; True once one accepted."""
        if job.id not in self._payloads:
            return True  # cancelled or finished under us: nothing to send
        tried: set[str] = set()
        outcomes: list[str] = []
        while True:
            replica = self._pick_excluding(tried)
            if replica is None:
                if outcomes and all(o == "errored" for o in outcomes):
                    # every live replica saw the payload and 5xx'd it:
                    # that is the submission's problem, not a transient
                    # hiccup — terminal-fail instead of requeueing a
                    # poison pill forever
                    self._payloads.pop(job.id, None)
                    self._note_state(job, "FAILED")
                    _flight.note(
                        "fleet_dispatch_failed", job=job.id,
                        attempts=job.attempts,
                        error=(job.error or {}).get("message"),
                    )
                    _flight.dump_soon(
                        "fleet_dispatch_failed",
                        extra={
                            "job": job.id,
                            "attempts": job.attempts,
                            "error": job.error,
                        },
                    )
                    return True
                return False
            tried.add(replica.url)
            job.attempts += 1
            outcome = await self._submit_to(replica, job)
            if outcome in ("accepted", "rejected"):
                return True
            outcomes.append(outcome)
            # "busy", "failed", and "errored" all fall through to the
            # next-best replica; note_failure already advanced the
            # ejection breaker on "failed"

    def _replica_hint(self) -> float:
        """The replicas' last 429 retryAfter, if RECENT — a spike hint
        from hours ago must not inflate today's 429s against an idle
        fleet, so it expires after a minute."""
        if time.monotonic() - self._hint_at > 60.0:
            return 0.0
        return self._last_replica_hint

    def _pick_excluding(self, tried: set) -> Replica | None:
        best = None
        for r in self.registry.replicas:
            if r.url in tried or r.state != ACTIVE:
                continue
            if best is None or r.score() < best.score():
                best = r
        return best

    async def _submit_to(self, replica: Replica, job: RoutedJob) -> str:
        """One dispatch attempt, recorded as a fleet.dispatch span so the
        stitched trace shows every replica the payload visited."""
        t0 = time.perf_counter()
        outcome = await self._submit_to_inner(replica, job)
        job.record_span(
            "fleet.dispatch", t0, time.perf_counter() - t0,
            replica=replica.name, outcome=outcome,
        )
        return outcome

    async def _submit_to_inner(self, replica: Replica, job: RoutedJob) -> str:
        fields = self._payloads.get(job.id)
        if fields is None:  # cancelled/handed off under us
            return "accepted"
        form = aiohttp.FormData()
        for name, value in fields.items():
            form.add_field(name, value, filename=name)
        form.add_field("job_id", job.id)
        # kind picks the replica endpoint: the verification plane has
        # its own submission routes (docs/VERIFY.md); prove/mpc_prove
        # share /jobs/prove (the mpc flag rides in the fields)
        endpoint = {
            "verify": "/jobs/verify",
            "aggregate": "/jobs/aggregate",
        }.get(job.kind, "/jobs/prove")
        try:
            async with self._session.post(
                f"{replica.url}{endpoint}",
                data=form,
                headers={
                    "X-DG16-Tenant": job.tenant,
                    "X-DG16-Priority": job.priority,
                    "X-DG16-Trace": job.trace_id,
                },
                timeout=aiohttp.ClientTimeout(total=600.0),
            ) as resp:
                body = await resp.json()
                status = resp.status
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as e:
            log.debug(
                "dispatch %s -> %s failed: %r", job.id, replica.name, e,
                extra={"job": job.id, "trace": job.trace_id},
            )
            self._note_replica_failure(replica, "dispatch")
            return "failed"
        if status in (200, 202):
            job.replica = replica
            job.state = body.get("state", "QUEUED")
            job.error = None  # a prior attempt's 5xx note is moot now
            # optimistic local load bump so a burst between polls doesn't
            # pile onto one replica's stale low score
            replica.doc["queueDepth"] = int(replica.doc.get("queueDepth", 0)) + 1
            self._payloads.pop(job.id, None)
            _ROUTED.labels(tenant=job.tenant, priority=job.priority).inc()
            # the router-tier breadcrumb in the job's federated log view
            # (GET /fleet/jobs/{id}/logs — docs/OBSERVABILITY.md)
            log.info(
                "dispatch %s -> %s accepted", job.id, replica.name,
                extra={"job": job.id, "trace": job.trace_id},
            )
            return "accepted"
        if status == 429:
            hint = body.get("retryAfter")
            if hint is not None:
                self._last_replica_hint = float(hint)
                self._hint_at = time.monotonic()
            return "busy"
        if status == 503:
            # draining: deliberate — stop routing there, don't eject
            replica.state = DRAINING
            return "busy"
        if status >= 500:
            # a replica-side internal error may be transient (a journal
            # fsync hitting a momentarily full disk) — remember the
            # message and let _dispatch try the next-best replica; it
            # terminal-fails only once EVERY live replica 5xx'd the
            # payload. Not fed to the ejection breaker: the replica
            # answered, so connectivity is fine.
            log.warning(
                "dispatch %s -> %s errored (HTTP %d): %s",
                job.id, replica.name, status, body.get("error"),
                extra={"job": job.id, "trace": job.trace_id},
            )
            job.error = {
                "type": "DispatchRejected",
                "message": str(body.get("error", f"HTTP {status}")),
            }
            return "errored"
        # a 4xx is the SUBMISSION's fault (malformed payload, unknown
        # circuit), not the replica's: terminal-fail the job at the
        # router. Feeding these into the ejection breaker would let one
        # poisoned payload, retried across the fleet, eject every
        # healthy replica — connectivity problems (the exception path
        # above) and failed /readyz polls are what ejection is for.
        log.warning(
            "dispatch %s -> %s rejected (HTTP %d): %s",
            job.id, replica.name, status, body.get("error"),
            extra={"job": job.id, "trace": job.trace_id},
        )
        job.error = {
            "type": "DispatchRejected",
            "message": str(body.get("error", f"HTTP {status}")),
        }
        self._payloads.pop(job.id, None)
        self._note_state(job, "FAILED")
        return "rejected"

    # -- HTTP handlers --------------------------------------------------------

    async def jobs_prove(self, request):
        return await self._jobs_submit(request, None)

    async def jobs_verify(self, request):
        return await self._jobs_submit(request, "verify")

    async def jobs_aggregate(self, request):
        return await self._jobs_submit(request, "aggregate")

    async def _jobs_submit(self, request, kind: str | None):
        """Front-door admission for every job kind. kind=None is the
        prove route (mpc flag picks prove/mpc_prove); "verify" and
        "aggregate" are the verification plane (docs/VERIFY.md) — same
        tenant buckets, same weighted-fair queue, same idempotent
        dispatch; only the replica endpoint differs (by job.kind)."""
        t_req0 = time.perf_counter()
        tenant = request.headers.get("X-DG16-Tenant", "").strip() \
            or DEFAULT_TENANT
        try:
            fields = await _read_multipart(request)
        except Exception as e:  # noqa: BLE001
            return _error(str(e))
        priority = (
            request.headers.get("X-DG16-Priority", "").strip()
            or fields.pop("priority", b"").decode().strip()
            or DEFAULT_PRIORITY
        )
        if self.draining:
            self.admission.note_rejected(tenant, "draining")
            return _error("fleet router is draining", status=503)
        if "circuit_id" not in fields:
            return _error("circuit_id field is required")
        if kind in ("verify", "aggregate") and "proofs_file" not in fields:
            return _error("proofs_file field is required", status=400)
        # decode BEFORE admit(): a slot charged for a submission that
        # then 500s on bad bytes would never be released (quota leak)
        try:
            circuit_id = fields["circuit_id"].decode()
            mpc = fields.get("mpc", b"").decode().lower() in ("1", "true", "yes")
        except UnicodeDecodeError:
            return _error("circuit_id / mpc fields must be UTF-8")
        if kind is None:
            kind = "mpc_prove" if mpc else "prove"
        if len(self.queue) >= self.cfg.pending_bound:
            self.admission.note_rejected(tenant, "backlog")
            return _busy(
                tenant, "backlog",
                max(self._replica_hint(), 5.0),
                f"fleet dispatch backlog full "
                f"({len(self.queue)}/{self.cfg.pending_bound} pending)",
            )
        try:
            self.admission.admit(tenant)
        except TenantQuotaError as e:
            # the promised hint: max over the tenant bucket and whatever
            # the replicas last said about their own queues
            return _busy(
                tenant, e.reason,
                max(e.retry_after_s, self._replica_hint()),
                str(e),
            )
        job = RoutedJob(
            id=uuid.uuid4().hex,
            tenant=tenant,
            priority=priority,
            circuit_id=circuit_id,
            kind=kind,
            # the end-to-end trace context is born here, next to the
            # idempotent job id: every router span, replica service
            # span, and MPC-party span downstream carries it
            trace_id=uuid.uuid4().hex,
        )
        job.record_span(
            "fleet.admission", t_req0, time.perf_counter() - t_req0,
            tenant=tenant, priority=priority,
        )
        self.jobs[job.id] = job
        self._payloads[job.id] = fields
        self._queue_job(job)
        if self._wake is not None:
            self._wake.set()
        return web.json_response(
            {
                "jobId": job.id,
                "traceId": job.trace_id,
                "tenant": tenant,
                "priority": priority,
                "state": job.state,
                "pending": len(self.queue),
            },
            status=202,
        )

    def _job_or_404(self, request) -> RoutedJob | web.Response:
        job = self.jobs.get(request.match_info["job_id"])
        if job is None:
            return _error("unknown job id", status=404)
        return job

    async def _proxy_job(self, request, suffix: str = "") -> web.Response:
        job = self._job_or_404(request)
        if isinstance(job, web.Response):
            return job
        # snapshot the owner: a concurrent handoff may null job.replica
        # while the proxy await is in flight
        replica = job.replica
        if replica is None:
            if suffix:
                if job.state == "FAILED":
                    return _error(
                        (job.error or {}).get("message", "job failed")
                    )
                if job.state == "CANCELLED":
                    return _error("job was cancelled", status=410)
                return _error(
                    f"job not dispatched yet (state {job.state})", 409
                )
            return web.json_response(job.to_dict())
        try:
            async with self._session.request(
                request.method,
                f"{replica.url}/jobs/{job.id}{suffix}",
                timeout=aiohttp.ClientTimeout(total=60.0),
            ) as resp:
                body = await resp.read()
                status = resp.status
                ctype = resp.content_type
        except (aiohttp.ClientError, asyncio.TimeoutError):
            _PROXY_ERRORS.labels(route=f"/jobs/{{job_id}}{suffix}").inc()
            self._note_replica_failure(replica, "proxy")
            return _error(
                f"replica {replica.name} unreachable "
                "(handoff will re-route the job)",
                status=503,
            )
        if status == 200 and not suffix:
            # piggyback state tracking on client polls — a DELETE body
            # carries the post-cancel state too (RUNNING jobs cancel
            # cooperatively, so CANCELLED only lands when it is real)
            try:
                self._note_state(job, json.loads(body).get("state", job.state))
            except ValueError:
                pass
        return web.Response(body=body, status=status, content_type=ctype)

    async def job_status(self, request):
        return await self._proxy_job(request)

    async def job_result(self, request):
        return await self._proxy_job(request, "/result")

    async def job_trace(self, request):
        return await self._proxy_job(request, "/trace")

    async def fleet_job_trace(self, request):
        """GET /fleet/jobs/{id}/trace — the STITCHED end-to-end trace:
        router-tier spans (admission, queue wait, dispatch attempts,
        handoff) plus the owning replica's merged job trace — service
        phases and MPC-party rounds — rebased onto the router's clock
        via the /readyz poll echoes, one Chrome trace out. Clicking any
        job shows the full router -> queue -> batch -> MPC-round
        critical path across all three tiers (docs/OBSERVABILITY.md
        "Fleet observatory")."""
        job = self.jobs.get(request.match_info["job_id"])
        if job is None:
            return _error("unknown job id", status=404)
        events = [dict(ev) for ev in job.spans]
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": ROUTER_PID,
                "args": {"name": "fleet router"},
            }
        ]
        warning = None
        # snapshot the owner: a concurrent handoff may null job.replica
        # while the trace fetch await is in flight
        replica = job.replica
        if replica is not None:
            body = None
            try:
                async with self._session.get(
                    f"{replica.url}/jobs/{job.id}/trace",
                    timeout=aiohttp.ClientTimeout(total=60.0),
                ) as resp:
                    if resp.status == 200:
                        body = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                body = None
            if body is None:
                warning = (
                    f"replica {replica.name} did not serve the job "
                    "trace; router spans only"
                )
                _PROXY_ERRORS.labels(
                    route="/fleet/jobs/{job_id}/trace"
                ).inc()
            else:
                # rebase replica perf_counter timestamps onto the
                # router's clock: ClockSync.offset_ns estimates
                # replica_clock − router_clock, so ADD its negation
                # (the PR 4 add_party convention)
                off_us = -replica.clock.offset_ns / 1e3
                pids: set[int] = set()
                for ev in body.get("traceEvents", []):
                    if not isinstance(ev, dict):
                        continue
                    ts = ev.get("ts")
                    if not isinstance(ts, (int, float)):
                        continue  # metadata/corrupt events don't rebase
                    ev = dict(ev)
                    ev["ts"] = ts + off_us
                    try:
                        pids.add(int(ev.get("pid", 0)))
                    except (TypeError, ValueError):
                        ev["pid"] = 0
                        pids.add(0)
                    events.append(ev)
                for p in sorted(pids):
                    name = f"replica {replica.name}"
                    if p != 0:
                        name += f" party {p}"
                    meta.append(
                        {
                            "name": "process_name",
                            "ph": "M",
                            "pid": p,
                            "args": {"name": name},
                        }
                    )
        events.sort(key=lambda e: e.get("ts", 0.0))
        out = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "traceId": job.trace_id,
        }
        if warning is not None:
            out["warning"] = warning
        return web.json_response(out)

    async def fleet_job_logs(self, request):
        """GET /fleet/jobs/{id}/logs — the job's CORRELATED log stream
        across tiers: the router's own structured records for this trace
        plus the owning replica's (`GET /logs?trace=`), rebased onto the
        router's clock with the same ClockSync offset the stitched trace
        uses. Every record gains `source` (router / replica name) and
        `tsRouterNs`; the merge is sorted on the latter, so an operator
        reads one causally-ordered story: admitted here, dispatched
        there, died on party 3 (docs/OBSERVABILITY.md "Logging spine").
        ?level= filters both sides; ?limit= caps each side's tail."""
        job = self.jobs.get(request.match_info["job_id"])
        if job is None:
            return _error("unknown job id", status=404)
        q = request.rel_url.query
        level = q.get("level")
        if level and level.upper() not in _logbus.LEVELS:
            return _error(
                "level must be one of DEBUG/INFO/WARNING/ERROR/CRITICAL",
                status=400,
            )
        try:
            limit = int(q.get("limit", "256"))
        except ValueError:
            return _error("limit must be an integer", status=400)
        # the router's own records for this trace. An in-process test
        # fleet shares ONE ring between router and replica, so keep only
        # fleet-tier loggers here — the replica's records arrive (once)
        # over HTTP below.
        records = [
            dict(r, source="router", tsRouterNs=r["tsPcNs"])
            for r in _logbus.ring().query(
                trace=job.trace_id, level=level, limit=limit
            )
            if r.get("logger", "").startswith("fleet")
        ]
        warning = None
        replica = job.replica  # snapshot: handoff may null it mid-await
        if replica is not None:
            body = None
            try:
                params = {"trace": job.trace_id, "limit": str(limit)}
                if level:
                    params["level"] = level
                async with self._session.get(
                    f"{replica.url}/logs",
                    params=params,
                    timeout=aiohttp.ClientTimeout(total=60.0),
                ) as resp:
                    if resp.status == 200:
                        body = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                body = None
            if body is None:
                warning = (
                    f"replica {replica.name} did not serve logs; "
                    "router records only"
                )
                _PROXY_ERRORS.labels(route="/fleet/jobs/{job_id}/logs").inc()
            else:
                # rebase: ClockSync.offset_ns estimates replica_clock −
                # router_clock over perf_counter_ns, so SUBTRACT it
                off_ns = replica.clock.offset_ns
                for r in body.get("records", []):
                    if not isinstance(r, dict):
                        continue
                    if str(r.get("logger", "")).startswith("fleet"):
                        # the shared-ring mirror of the dedup above: an
                        # in-process replica echoes the router's own
                        # records back — they're already counted
                        continue
                    ts = r.get("tsPcNs")
                    if not isinstance(ts, (int, float)):
                        continue
                    r = dict(r)
                    r["source"] = f"replica {replica.name}"
                    r["tsRouterNs"] = ts - off_ns
                    records.append(r)
        records.sort(key=lambda r: r.get("tsRouterNs", 0))
        out = {
            "jobId": job.id,
            "traceId": job.trace_id,
            "records": records,
        }
        if warning is not None:
            out["warning"] = warning
        return web.json_response(out)

    async def job_cancel(self, request):
        job = self._job_or_404(request)
        if isinstance(job, web.Response):
            return job
        if job.replica is None:
            job.cancelled = True
            self._note_state(job, "CANCELLED")
            return web.json_response(
                {"jobId": job.id, "state": "CANCELLED",
                 "cancelRequested": False}
            )
        return await self._proxy_job(request)

    # -- fleet control plane --------------------------------------------------

    def _pending_by_kind(self) -> dict[str, int]:
        """Undispatched depth per job kind — how much prove vs verify
        work waits at the front door (`fleet top`, docs/VERIFY.md)."""
        out: dict[str, int] = {}
        for j in self.jobs.values():
            if j.state == "PENDING" and not j.cancelled:
                out[j.kind] = out.get(j.kind, 0) + 1
        return out

    async def fleet_stats(self, request):
        return web.json_response(
            {
                "replicas": self.registry.stats(),
                "tenants": self.admission.stats(),
                "pending": len(self.queue),
                "pendingByClass": self.queue.occupancy(),
                "pendingByKind": self._pending_by_kind(),
                "weights": dict(self.cfg.weights),
                "handoffs": self.handoffs,
                "jobsTracked": len(self.jobs),
                "federation": {
                    "replicasScraped": len(self.federator.replicas()),
                    "scrapesOk": self.federator.scrapes_ok,
                    "scrapesFailed": self.federator.scrapes_failed,
                    "seriesSkipped": self.federator.series_skipped,
                },
            }
        )

    async def fleet_metrics(self, request):
        """GET /fleet/metrics — the federated exposition: every live
        replica's series re-exported with a `replica` label plus the
        fleet rollups (docs/OBSERVABILITY.md "Fleet observatory"). The
        router's own families stay on /metrics."""
        return web.Response(
            text=self.federator.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def fleet_drain(self, request):
        """Operator drain without SIGTERM access (docs/FLEET.md): ask the
        replica to stop admitting, then hand its backlog off NOW."""
        name = request.match_info["replica"]
        replica = self.registry.find(name)
        if replica is None:
            return _error(f"unknown replica {name!r}", status=404)
        try:
            async with self._session.post(
                f"{replica.url}/drain",
                timeout=aiohttp.ClientTimeout(total=30.0),
            ) as resp:
                ok = resp.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError):
            _PROXY_ERRORS.labels(route="/fleet/drain/{replica}").inc()
            ok = False
        if not ok and replica.state != EJECTED:
            return _error(
                f"replica {replica.name} did not acknowledge the drain",
                status=502,
            )
        replica.state = DRAINING
        replica.handoff_done = False
        moved = await self._handoff(replica)
        return web.json_response(
            {
                "replica": replica.name,
                "state": "draining",
                "handedOff": moved,
            }
        )

    async def healthz(self, request):
        return web.json_response(
            {
                "status": "draining" if self.draining else "ok",
                "replicas": len(self.registry.replicas),
                "activeReplicas": self.registry.active_count(),
                "pending": len(self.queue),
            }
        )

    async def readyz(self, request):
        """The router is ready when it could place a job somewhere."""
        ready = self.registry.active_count() > 0 and not self.draining
        return web.json_response(
            {"status": "ok" if ready else "no active replicas"},
            status=200 if ready else 503,
        )

    async def metrics(self, request):
        return web.Response(
            text=_tm.registry().render_prometheus(),
            content_type="text/plain",
            charset="utf-8",
        )

    # -- app ------------------------------------------------------------------

    @web.middleware
    async def _http_middleware(self, request, handler):
        """Front-door latency histogram per (route template, status):
        the router's own cost, separable from replica latency. The label
        is the matched ROUTE (bounded cardinality — unmatched paths all
        land on "unmatched"), never the raw path."""
        t0 = time.perf_counter()
        code = 500
        try:
            resp = await handler(request)
            code = resp.status
            return resp
        except web.HTTPException as e:
            code = e.status
            raise
        finally:
            resource = (
                request.match_info.route.resource
                if request.match_info.route is not None
                else None
            )
            route = (
                resource.canonical if resource is not None else "unmatched"
            )
            _HTTP_SECONDS.labels(route=route, code=str(code)).observe(
                time.perf_counter() - t0
            )

    def app(self) -> web.Application:
        app = web.Application(
            client_max_size=MAX_BODY, middlewares=[self._http_middleware]
        )
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        app.router.add_post("/jobs/prove", self.jobs_prove)
        app.router.add_post("/jobs/verify", self.jobs_verify)
        app.router.add_post("/jobs/aggregate", self.jobs_aggregate)
        app.router.add_get("/jobs/{job_id}", self.job_status)
        app.router.add_get("/jobs/{job_id}/result", self.job_result)
        app.router.add_get("/jobs/{job_id}/trace", self.job_trace)
        app.router.add_delete("/jobs/{job_id}", self.job_cancel)
        app.router.add_get("/fleet/stats", self.fleet_stats)
        app.router.add_get("/fleet/metrics", self.fleet_metrics)
        app.router.add_get(
            "/fleet/jobs/{job_id}/trace", self.fleet_job_trace
        )
        app.router.add_get(
            "/fleet/jobs/{job_id}/logs", self.fleet_job_logs
        )
        # {replica:.+}: the operand may be the config URL itself
        # (slashes and all) — `find` accepts either spelling
        app.router.add_post("/fleet/drain/{replica:.+}", self.fleet_drain)
        app.router.add_get("/healthz", self.healthz)
        app.router.add_get("/readyz", self.readyz)
        app.router.add_get("/metrics", self.metrics)
        return app


def main() -> None:
    # console + ring via the one logging entry point (DG16_LOG_LEVEL /
    # DG16_LOG_JSON) — basicConfig would bypass the structured spine
    _logbus.setup()
    port = int(os.environ.get("PORT", "8080"))
    router = FleetRouter()
    if not router.registry.replicas:
        raise SystemExit(
            "no replicas configured — set DG16_FLEET_REPLICAS "
            "(docs/FLEET.md)"
        )
    web.run_app(router.app(), port=port)
