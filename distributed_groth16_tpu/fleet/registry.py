"""Replica registry: pull-based discovery, scoring, and ejection.

The router never holds a connection-level view of replica health; it
POLLS. Every `poll_s` it GETs each replica's `/readyz` — which since the
fleet PR returns a one-stop JSON **capacity document** (replica id,
device inventory, open breaker count, drain flag, queue shape, SLO burn
summary; docs/FLEET.md) — and folds the answer into a scored table:

    score = (queued + running + 1) / workers * (1 + max SLO burn rate)

Lower is better: the least-loaded replica wins, but a replica eating its
error budget (slo_burn_rate > 1, PR 8) looks proportionally worse than
its raw queue depth says, so traffic drifts away from a replica that is
slow *before* it is full. Dispatch picks the minimum-score ACTIVE
replica (not draining, breaker closed).

Ejection reuses the PR 7 breaker state machine shape (closed ->
open/cooldown -> half-open single probe): `eject_threshold` consecutive
failures — poll errors, connection refusals, 5xx dispatches — trip the
replica out of rotation; after `eject_cooldown_s` ONE probe poll may
readmit it. A replica that 503s because it is DRAINING is not ejected
(it answered; it is deliberately finishing work) but stops receiving new
jobs, and either state hands its journaled backlog to the router's
handoff pass (fleet/router.py).

Pure event-loop-side state; the HTTP GET itself is the router's (async)
job — the registry only ingests outcomes, so it is unit-testable with
canned documents and an injectable clock.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..telemetry import metrics as _tm
from ..telemetry.aggregate import ClockSync

_REG = _tm.registry()
_SCORE = _REG.gauge(
    "fleet_replica_score",
    "Routing score per replica (lower = preferred): load weighted by "
    "SLO burn rate; -1 while the replica is out of rotation",
    ("replica",),
)
_STATE = _REG.gauge(
    "fleet_replica_state",
    "Replica rotation state: 0 active, 1 draining, 2 ejected "
    "(cooling down / probing)",
    ("replica",),
)
_EJECTIONS = _REG.counter(
    "fleet_replica_ejections_total",
    "Replicas ejected from rotation (consecutive-failure breaker trips)",
    ("replica",),
)

# gauge values are part of the dashboard contract (docs/FLEET.md)
ACTIVE, DRAINING, EJECTED = 0, 1, 2

_STATE_NAMES = {ACTIVE: "active", DRAINING: "draining", EJECTED: "ejected"}


@dataclass
class Replica:
    """One replica as the router knows it."""

    url: str
    journal_dir: str | None = None
    # identity: the url is the stable config name; `replica_id` is what
    # the replica itself reports (DG16_FLEET_REPLICA_ID) once a poll
    # succeeded — operator commands accept either
    replica_id: str = ""
    doc: dict = field(default_factory=dict)  # last capacity document
    state: int = ACTIVE
    failures: int = 0  # consecutive, feeds the ejection breaker
    ejected_at: float = 0.0
    probing: bool = False  # half-open: one probe in flight max
    handoff_done: bool = False  # this outage's backlog already re-routed
    polls_ok: int = 0
    polls_failed: int = 0
    # NTP-style offset estimate (replica_clock − router_clock) fed by
    # the /readyz poll's clock echo — what rebases this replica's trace
    # events onto the router's timeline in the stitched fleet trace
    clock: ClockSync = field(default_factory=ClockSync, repr=False)

    @property
    def name(self) -> str:
        return self.replica_id or self.url

    def score(self) -> float:
        """Routing score from the last capacity document (lower wins)."""
        doc = self.doc
        workers = max(1, int(doc.get("workers", 1)))
        load = (
            int(doc.get("queueDepth", 0)) + int(doc.get("running", 0)) + 1
        ) / workers
        burn = max(0.0, float(doc.get("maxBurnRate", 0.0) or 0.0))
        return load * (1.0 + burn)


class ReplicaRegistry:
    def __init__(
        self,
        replicas,  # ((url, journal_dir | None), ...)
        eject_threshold: int = 3,
        eject_cooldown_s: float = 15.0,
        clock=time.monotonic,
    ):
        self.eject_threshold = eject_threshold
        self.eject_cooldown_s = eject_cooldown_s
        self._clock = clock
        self.replicas: list[Replica] = [
            Replica(url=url, journal_dir=jdir) for url, jdir in replicas
        ]
        for r in self.replicas:
            _STATE.labels(replica=r.name).set(ACTIVE)

    def find(self, name: str) -> Replica | None:
        """By reported id or config URL (operator commands take either)."""
        for r in self.replicas:
            if name in (r.replica_id, r.url, r.name):
                return r
        return None

    # -- poll/dispatch outcome ingestion -------------------------------------

    def note_doc(self, replica: Replica, doc: dict) -> None:
        """A successful /readyz poll (HTTP 200 *or* a parsed 503-drain
        body): refresh the capacity document and the breaker."""
        replica.doc = doc
        replica.polls_ok += 1
        if replica.replica_id == "" and doc.get("replicaId"):
            # first contact: adopt the replica's self-reported id for
            # metrics/commands, migrating the placeholder gauge labels —
            # the URL-labeled series must go, or dashboards see a
            # phantom always-active replica per configured URL
            old = replica.name
            replica.replica_id = str(doc["replicaId"])
            if replica.name != old:
                _STATE.remove(replica=old)
                _SCORE.remove(replica=old)
                # a pre-adoption ejection (unreachable at boot, then
                # recovered) counted under the URL label: carry the
                # count over so one replica's ejections stay one series
                ejected = dict(_EJECTIONS.items()).get((old,))
                if ejected is not None:
                    _EJECTIONS.remove(replica=old)
                    if ejected.value:
                        _EJECTIONS.labels(replica=replica.name).inc(
                            ejected.value
                        )
        draining = bool(doc.get("draining"))
        if replica.state == EJECTED:
            # probe succeeded: the replica answers again. Its journal
            # may hold jobs accepted before the outage — clear the
            # handoff latch only AFTER recovery so the next outage
            # hands off again.
            replica.probing = False
            replica.failures = 0
            replica.handoff_done = False
        replica.state = DRAINING if draining else ACTIVE
        if replica.state == ACTIVE:
            replica.handoff_done = False
        replica.failures = 0
        self._export(replica)

    def note_failure(self, replica: Replica) -> bool:
        """A failed poll or dispatch (connect error, timeout, 5xx).
        Returns True when THIS failure ejects the replica."""
        replica.polls_failed += 1
        if replica.state == EJECTED:
            # a failed half-open probe re-opens the cooldown
            replica.probing = False
            replica.ejected_at = self._clock()
            self._export(replica)
            return False
        if self.eject_threshold <= 0:
            return False
        replica.failures += 1
        if replica.failures >= self.eject_threshold:
            replica.state = EJECTED
            replica.ejected_at = self._clock()
            replica.probing = False
            _EJECTIONS.labels(replica=replica.name).inc()
            self._export(replica)
            return True
        self._export(replica)
        return False

    def pollable(self) -> list[Replica]:
        """Who the discovery loop should GET this tick: every ACTIVE and
        DRAINING replica, plus ejected ones whose cooldown lapsed (one
        half-open probe each)."""
        now = self._clock()
        out = []
        for r in self.replicas:
            if r.state != EJECTED:
                out.append(r)
            elif (
                not r.probing
                and now - r.ejected_at >= self.eject_cooldown_s
            ):
                r.probing = True
                out.append(r)
        return out

    # -- routing --------------------------------------------------------------

    def pick(self) -> Replica | None:
        """The dispatch target: minimum score over ACTIVE replicas."""
        best = None
        for r in self.replicas:
            if r.state != ACTIVE:
                continue
            if best is None or r.score() < best.score():
                best = r
        return best

    def active_count(self) -> int:
        return sum(1 for r in self.replicas if r.state == ACTIVE)

    def needs_handoff(self) -> list[Replica]:
        """Replicas whose journaled backlog should be re-routed now:
        dead (ejected) or draining, not yet handed off this outage."""
        return [
            r
            for r in self.replicas
            if r.state in (EJECTED, DRAINING) and not r.handoff_done
        ]

    def _export(self, replica: Replica) -> None:
        _STATE.labels(replica=replica.name).set(replica.state)
        _SCORE.labels(replica=replica.name).set(
            replica.score() if replica.state == ACTIVE else -1.0
        )

    def stats(self) -> list[dict]:
        """The /fleet/stats replica table (docs/FLEET.md)."""
        rows = []
        for r in self.replicas:
            doc = r.doc
            rows.append(
                {
                    "replicaId": r.name,
                    "url": r.url,
                    "state": _STATE_NAMES[r.state],
                    "score": round(r.score(), 3) if doc else None,
                    "queueDepth": doc.get("queueDepth"),
                    "running": doc.get("running"),
                    "workers": doc.get("workers"),
                    "devices": doc.get("devices"),
                    "openBreakers": doc.get("openBreakers"),
                    "maxBurnRate": doc.get("maxBurnRate"),
                    # the replica's /readyz buildInfo (package version) —
                    # `fleet top` renders it so a rolling upgrade shows
                    # up as a mixed VER column
                    "version": (doc.get("buildInfo") or {}).get("version"),
                    "journal": r.journal_dir,
                    "pollsOk": r.polls_ok,
                    "pollsFailed": r.polls_failed,
                    "clockOffsetS": (
                        round(r.clock.offset_ns / 1e9, 6)
                        if r.clock.n_samples
                        else None
                    ),
                }
            )
        return rows
