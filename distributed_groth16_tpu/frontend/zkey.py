"""snarkjs `.zkey` proving-key reader/writer (Groth16, BN254).

Binary-format parity with the reference's zkey parser
(ark-circom/src/zkey.rs:53-385): a `zkey` section container holding

  1  ProverType          u32 == 1 (Groth16)
  2  HeaderGroth         n8q, q, n8r, r, nVars, nPub, domainSize, then the
                         vk points alpha1 beta1 beta2 gamma2 delta1 delta2
  3  IC                  (nPub+1) G1   — gamma_abc
  4  Coefs               u32 count, then (matrix, constraint, signal) u32
                         triples + an Fr value per nonzero of A and B,
                         including one synthetic A-row per public signal
                         (the rows arkworks re-adds itself, zkey.rs:164-177)
  5  PointsA             nVars G1
  6  PointsB1            nVars G1
  7  PointsB2            nVars G2
  8  PointsC             (nVars - nPub - 1) G1 — l_query
  9  PointsH             domainSize G1
  10 Contributions       ignored (zkey.rs reads nothing from it)

Field encodings (zkey.rs:330-352): Fq coordinates are stored in Montgomery
form (raw = x * 2^256 mod q), which is byte-identical to this framework's
device limb layout (ops/field.py encode), so point sections parse as one
vectorized `np.frombuffer` with no bigint work. Fr matrix coefficients are
stored multiplied by R^2 (raw = v * 2^512 mod r, the double-division in
zkey.rs:331-334). Infinity encodes as all-zero coordinates.
"""

from __future__ import annotations

import io
import struct

import jax.numpy as jnp
import numpy as np

from ..models.groth16.keys import ProvingKey, VerifyingKey
from ..ops.constants import Q, R
from .r1cs import R1CS

_MONT = 1 << 256
_MONT_Q = _MONT % Q
_MONT_Q_INV = pow(_MONT_Q, Q - 2, Q)
_MONT_R = _MONT % R
_MONT_R_INV = pow(_MONT_R, R - 2, R)

_MAGIC = b"zkey"


# ---------------------------------------------------------------------------
# low-level field/point codecs
# ---------------------------------------------------------------------------


def _fq_mont_bytes(x_std: int) -> bytes:
    return (x_std * _MONT_Q % Q).to_bytes(32, "little")


def _fr_r2_bytes(v_std: int) -> bytes:
    """Fr coefficient as stored: v * R^2 mod r (zkey.rs:329-334)."""
    return (v_std * _MONT_R % R * _MONT_R % R).to_bytes(32, "little")


def _limbs_to_mont_bytes(arr: np.ndarray) -> bytes:
    """uint32 limb array (... , 16) -> raw Montgomery bytes, vectorized."""
    return np.ascontiguousarray(arr).astype("<u2").tobytes()


def _g1_array_from_bytes(buf: bytes, n: int) -> jnp.ndarray:
    """n * 64 bytes of (x, y) Montgomery coords -> (n, 3, 16) device
    projective limbs. Zero coords = infinity -> (0, 1, 0)."""
    raw = np.frombuffer(buf, dtype="<u2", count=n * 32).astype(np.uint32)
    xy = raw.reshape(n, 2, 16)
    inf = ~np.any(xy.reshape(n, -1), axis=1)
    one = np.zeros((16,), np.uint32)
    one_bytes = np.frombuffer(_fq_mont_bytes(1), dtype="<u2").astype(np.uint32)
    one[:] = one_bytes
    z = np.where(inf[:, None], 0, one[None, :]).astype(np.uint32)
    y = np.where(inf[:, None], one[None, :], xy[:, 1]).astype(np.uint32)
    return jnp.asarray(np.stack([xy[:, 0], y, z], axis=1))


def _g2_array_from_bytes(buf: bytes, n: int) -> jnp.ndarray:
    """n * 128 bytes of (x.c0, x.c1, y.c0, y.c1) -> (n, 3, 2, 16)."""
    raw = np.frombuffer(buf, dtype="<u2", count=n * 64).astype(np.uint32)
    xy = raw.reshape(n, 2, 2, 16)
    inf = ~np.any(xy.reshape(n, -1), axis=1)
    one = np.frombuffer(_fq_mont_bytes(1), dtype="<u2").astype(np.uint32)
    zero16 = np.zeros((16,), np.uint32)
    fq2_one = np.stack([one, zero16], axis=0)  # Fq2 one = (1, 0)
    z = np.where(inf[:, None, None], 0, fq2_one[None]).astype(np.uint32)
    # infinity encodes as the projective (0, 1, 0)
    y = np.where(inf[:, None, None], fq2_one[None], xy[:, 1]).astype(np.uint32)
    return jnp.asarray(np.stack([xy[:, 0], y, z], axis=1))


def _g1_bytes_from_limbs(pts_proj: jnp.ndarray) -> bytes:
    """(n, 3, 16) projective device points -> n*64 affine Montgomery bytes."""
    from ..ops.curve import g1

    aff = np.asarray(g1().to_affine(pts_proj))  # (n, 2, 16); inf -> zeros
    return _limbs_to_mont_bytes(aff)


def _g2_bytes_from_limbs(pts_proj: jnp.ndarray) -> bytes:
    from ..ops.curve import g2

    aff = np.asarray(g2().to_affine(pts_proj))  # (n, 2, 2, 16)
    return _limbs_to_mont_bytes(aff)


def _host_g1(x_mont: int, y_mont: int):
    if x_mont == 0 and y_mont == 0:
        return None
    return (x_mont * _MONT_Q_INV % Q, y_mont * _MONT_Q_INV % Q)


def _host_g2(coords: list[int]):
    if all(c == 0 for c in coords):
        return None
    x0, x1, y0, y1 = (c * _MONT_Q_INV % Q for c in coords)
    return ((x0, x1), (y0, y1))


# ---------------------------------------------------------------------------
# container
# ---------------------------------------------------------------------------


def _parse_sections(data: bytes) -> dict[int, tuple[int, int]]:
    if data[:4] != _MAGIC:
        raise ValueError("bad zkey magic")
    version, n_sections = struct.unpack_from("<II", data, 4)
    if version > 2:
        raise ValueError(f"unsupported zkey version {version}")
    out = {}
    pos = 12
    for _ in range(n_sections):
        typ, size = struct.unpack_from("<IQ", data, pos)
        pos += 12
        out[typ] = (pos, size)
        pos += size
    return out


def read_zkey(path_or_bytes) -> tuple[ProvingKey, R1CS]:
    """Parse a snarkjs `.zkey` into (ProvingKey, constraint matrices).

    The returned R1CS carries the A/B matrices stored in the Coefs section
    (C is not stored in zkey files — zkey.rs:193-196 returns it empty); its
    `c` rows are empty lists. Mirrors ark-circom's read_zkey
    (zkey.rs:53-60).
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    secs = _parse_sections(data)

    # -- header (2) --
    pos, _ = secs[2]
    n8q = struct.unpack_from("<I", data, pos)[0]
    if n8q != 32:
        raise ValueError("only 32-byte base fields supported")
    q = int.from_bytes(data[pos + 4 : pos + 36], "little")
    if q != Q:
        raise ValueError("zkey base field is not BN254 Fq")
    n8r = struct.unpack_from("<I", data, pos + 36)[0]
    r = int.from_bytes(data[pos + 40 : pos + 72], "little")
    if n8r != 32 or r != R:
        raise ValueError("zkey scalar field is not BN254 Fr")
    n_vars, n_public, domain_size = struct.unpack_from("<III", data, pos + 72)
    vkpos = pos + 84
    # alpha1, beta1, beta2, gamma2, delta1, delta2
    w = [
        int.from_bytes(data[vkpos + 32 * i : vkpos + 32 * (i + 1)], "little")
        for i in range(2 + 2 + 4 + 4 + 2 + 4)
    ]
    alpha_g1 = _host_g1(w[0], w[1])
    beta_g1_h = _host_g1(w[2], w[3])
    beta_g2 = _host_g2(w[4:8])
    gamma_g2 = _host_g2(w[8:12])
    delta_g1_h = _host_g1(w[12], w[13])
    delta_g2 = _host_g2(w[14:18])

    # -- point sections --
    def g1_sec(sid: int, n: int) -> jnp.ndarray:
        pos, size = secs[sid]
        if size < n * 64:
            raise ValueError(f"zkey section {sid} truncated")
        return _g1_array_from_bytes(data[pos : pos + n * 64], n)

    def g2_sec(sid: int, n: int) -> jnp.ndarray:
        pos, size = secs[sid]
        if size < n * 128:
            raise ValueError(f"zkey section {sid} truncated")
        return _g2_array_from_bytes(data[pos : pos + n * 128], n)

    ic = g1_sec(3, n_public + 1)
    a_query = g1_sec(5, n_vars)
    b_g1_query = g1_sec(6, n_vars)
    b_g2_query = g2_sec(7, n_vars)
    l_query = g1_sec(8, n_vars - n_public - 1)
    h_query = g1_sec(9, domain_size)

    from ..ops.curve import g1 as _g1curve

    gamma_abc = _g1curve().decode(ic)
    if not isinstance(gamma_abc, list):
        gamma_abc = [gamma_abc]

    vk = VerifyingKey(
        alpha_g1=alpha_g1,
        beta_g2=beta_g2,
        gamma_g2=gamma_g2,
        delta_g2=delta_g2,
        gamma_abc_g1=gamma_abc,
    )
    beta_g1_d = _g1curve().encode([beta_g1_h])[0]
    delta_g1_d = _g1curve().encode([delta_g1_h])[0]
    pk = ProvingKey(
        vk=vk,
        beta_g1=beta_g1_d,
        delta_g1=delta_g1_d,
        a_query=a_query,
        b_g1_query=b_g1_query,
        b_g2_query=b_g2_query,
        h_query=h_query,
        l_query=l_query,
        domain_size=domain_size,
        num_instance=n_public + 1,
    )

    # -- Coefs (4): A/B matrices -- (zkey.rs:150-198)
    pos, _ = secs[4]
    (n_coeffs,) = struct.unpack_from("<I", data, pos)
    pos += 4
    rows_a: dict[int, list] = {}
    rows_b: dict[int, list] = {}
    # one vectorized frombuffer over the fixed 44-byte records, then a
    # single Montgomery-correction pass over the UNIQUE coefficient
    # patterns (real circuits use a handful — mostly ±1): the per-record
    # struct.unpack + 256-bit multiply this replaces costs minutes of
    # Python at million-constraint scale
    rec = np.dtype(
        [("m", "<u4"), ("c", "<u4"), ("s", "<u4"), ("v", "V32")]
    )
    arr = np.frombuffer(data, dtype=rec, count=n_coeffs, offset=pos)
    rinv2 = _MONT_R_INV * _MONT_R_INV % R
    max_constraint = int(arr["c"].max()) if n_coeffs else 0
    uniq, inv_idx = np.unique(arr["v"], return_inverse=True)
    uvals = [
        int.from_bytes(u.tobytes(), "little") * rinv2 % R for u in uniq
    ]
    for matrix, constraint, signal, vi in zip(
        arr["m"].tolist(), arr["c"].tolist(), arr["s"].tolist(),
        inv_idx.tolist(),
    ):
        (rows_a if matrix == 0 else rows_b).setdefault(constraint, []).append(
            (uvals[vi], signal)
        )
    # drop the synthetic public-input rows arkworks re-adds (zkey.rs:173-177)
    num_constraints = max_constraint - n_public
    a = [rows_a.get(j, []) for j in range(num_constraints)]
    b = [rows_b.get(j, []) for j in range(num_constraints)]
    matrices = R1CS(
        num_instance=n_public + 1,
        num_witness=n_vars - n_public - 1,
        a=a,
        b=b,
        c=[[] for _ in range(num_constraints)],
    )
    return pk, matrices


def write_zkey(pk: ProvingKey, r1cs: R1CS) -> bytes:
    """Serialize a ProvingKey (+ its circuit's A/B matrices) to the snarkjs
    `.zkey` binary format, inverse of read_zkey. Emits the synthetic
    public-input A-rows the reference reader strips (zkey.rs:164-177) so
    external snarkjs/ark-circom tooling parses the file identically."""
    n_public = pk.num_instance - 1
    n_vars = pk.num_wires
    if r1cs.num_instance != pk.num_instance:
        raise ValueError("r1cs/proving-key instance-count mismatch")

    vk = pk.vk

    def g1h(pt) -> bytes:
        if pt is None:
            return b"\x00" * 64
        return _fq_mont_bytes(pt[0]) + _fq_mont_bytes(pt[1])

    def g2h(pt) -> bytes:
        if pt is None:
            return b"\x00" * 128
        (x0, x1), (y0, y1) = pt
        return b"".join(_fq_mont_bytes(c) for c in (x0, x1, y0, y1))

    header = struct.pack("<I", 32) + Q.to_bytes(32, "little")
    header += struct.pack("<I", 32) + R.to_bytes(32, "little")
    header += struct.pack("<III", n_vars, n_public, pk.domain_size)
    header += g1h(vk.alpha_g1)
    header += _limbs_to_mont_bytes(
        np.asarray(_affine_pair(pk.beta_g1, g2=False))
    )
    header += g2h(vk.beta_g2)
    header += g2h(vk.gamma_g2)
    header += _limbs_to_mont_bytes(
        np.asarray(_affine_pair(pk.delta_g1, g2=False))
    )
    header += g2h(vk.delta_g2)

    # Coefs: A and B nonzeros + synthetic A-rows for signals 0..n_public
    coefs = io.BytesIO()
    nc = r1cs.num_constraints
    entries = 0
    for matrix, rows in ((0, r1cs.a), (1, r1cs.b)):
        for j, row in enumerate(rows):
            for coeff, wire in row:
                coefs.write(struct.pack("<III", matrix, j, wire))
                coefs.write(_fr_r2_bytes(coeff))
                entries += 1
    for i in range(n_public + 1):
        coefs.write(struct.pack("<III", 0, nc + i, i))
        coefs.write(_fr_r2_bytes(1))
        entries += 1
    coefs_payload = struct.pack("<I", entries) + coefs.getvalue()

    from ..ops.curve import g1 as _g1curve

    ic_dev = _g1curve().encode(vk.gamma_abc_g1)

    sections = [
        (1, struct.pack("<I", 1)),
        (2, header),
        (3, _g1_bytes_from_limbs(ic_dev)),
        (4, coefs_payload),
        (5, _g1_bytes_from_limbs(pk.a_query)),
        (6, _g1_bytes_from_limbs(pk.b_g1_query)),
        (7, _g2_bytes_from_limbs(pk.b_g2_query)),
        (8, _g1_bytes_from_limbs(pk.l_query)),
        (9, _g1_bytes_from_limbs(pk.h_query)),
        (10, struct.pack("<I", 0)),  # zero contributions
    ]
    buf = io.BytesIO()
    buf.write(_MAGIC + struct.pack("<II", 1, len(sections)))
    for typ, payload in sections:
        buf.write(struct.pack("<IQ", typ, len(payload)))
        buf.write(payload)
    return buf.getvalue()


def _affine_pair(pt_proj: jnp.ndarray, g2: bool) -> jnp.ndarray:
    """Single projective device point -> (2,[2,]16) affine limbs."""
    from ..ops import curve as _curve

    C = _curve.g2() if g2 else _curve.g1()
    return C.to_affine(pt_proj[None])[0]
