"""ctypes binding for the native WASM execution tier (csrc/wasm_exec.c).

`CInstance` is drop-in for wasm_vm.Instance (same `call`/`memory`/`globals`
surface the witness calculator uses) but executes function bodies in C —
the wasmer role of the reference (witness_calculator.rs:56-153) without a
binary dependency: the .so is built on demand from the checked-in C source
with the system compiler and cached beside it. Falls back (ImportError
from `load_engine`) when no compiler is available; callers then keep the
pure-Python VM.

The C engine consumes wasm_vm.Module's pre-decoded instruction quads
verbatim, so the two engines are differential-testable against each other
(tests/test_wasm_cexec.py) and share all parsing/validation logic.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np

from .wasm_vm import PAGE, HostExit, Module, WasmTrap

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
    "wasm_exec.c",
)

_TRAP_MSG = {
    1: "unreachable",
    2: "division by zero",
    3: "integer overflow",
    4: "undefined table element",
    5: "unsupported opcode",
    6: "stack overflow",
    8: "memory.grow beyond maximum",
    9: "out-of-bounds memory access",
}


class WasmMemoryLimit(WasmTrap):
    """The C tier's linear-memory ceiling was hit (trap 8). Auto-engine
    callers fall back to the unbounded Python VM on this — and only
    this — trap class."""

_HOSTFN = ctypes.CFUNCTYPE(
    ctypes.c_uint64,
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int32),
)

_lib = None


def load_engine():
    """Compile (once, cached by source hash) and load the C engine."""
    global _lib
    if _lib is not None:
        return _lib
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    build_dir = os.path.join(os.path.dirname(_SRC), "build")
    so_path = os.path.join(build_dir, f"wasm_exec-{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(build_dir, exist_ok=True)
        cc = os.environ.get("CC", "cc")
        tmp = so_path + f".tmp{os.getpid()}"
        try:
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            raise ImportError(f"cannot build wasm_exec.so: {e}") from e
        os.replace(tmp, so_path)  # atomic vs concurrent builders
    lib = ctypes.CDLL(so_path)
    lib.wx_new.restype = ctypes.c_void_p
    I64P = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
    lib.wx_new.argtypes = [
        I64P, ctypes.c_int64,          # ins_flat, n_ins
        I64P, ctypes.c_int64,          # func_off, nfuncs
        I64P, I64P, I64P,              # func_locals/nparams/nresults
        I64P, I64P,                    # type_nparams/nresults
        I64P, I64P, ctypes.c_int64,    # imp_nparams/nresults, n_imports
        I64P, ctypes.c_int64,          # br_pool, n_pool
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,  # table, ntable
        ctypes.POINTER(ctypes.c_int64),                  # globals
        ctypes.POINTER(ctypes.c_uint8),                  # memory
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,  # cur_pages, max
        _HOSTFN,
    ]
    lib.wx_call.restype = ctypes.c_int32
    lib.wx_call.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.wx_free.restype = None
    lib.wx_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def _serialize(m: Module):
    """Module -> the flat int64 arrays the C engine consumes.

    Validates every module-supplied index here, at load time: the C
    executor trusts local/global/call indices (the Python VM's IndexError
    safety net doesn't exist there), and the module may be
    client-uploaded."""
    ins_rows = []
    func_off = [0]
    br_pool = []
    nglobals = len(m.globals_init)
    nfuncs_total = len(m.func_imports) + len(m.functions)
    for fn in m.functions:
        nloc = len(m.types[fn.type_idx].params) + fn.locals_n
        for op, a, b, c in fn.code:
            if op in (0x20, 0x21, 0x22) and not 0 <= a < nloc:
                raise WasmTrap(f"local index {a} out of range")
            if op in (0x23, 0x24) and not 0 <= a < nglobals:
                raise WasmTrap(f"global index {a} out of range")
            if op == 0x10 and not 0 <= a < nfuncs_total:
                raise WasmTrap(f"call target {a} out of range")
            if op == 0x11 and not 0 <= a < len(m.types):
                raise WasmTrap(f"call_indirect type {a} out of range")
            if op in (0x02, 0x04, 0x05) and b < 0:
                # a truncated body leaves end/else pcs unpatched (-1); the
                # C engine would jump to pc=-1 and execute garbage quads
                raise WasmTrap("unterminated control structure")
            if op == 0x0E:  # br_table: a=targets list, b=default
                ins_rows.append((op, len(br_pool), len(a), b))
                br_pool.extend(a)
            else:
                if a >= 1 << 63:  # u64 const -> two's-complement int64
                    a -= 1 << 64
                ins_rows.append((op, a, b, c))
        func_off.append(len(ins_rows))
    ins = np.array(ins_rows, dtype=np.int64).reshape(-1, 4)
    ntypes_pad = 1024  # engine copies a fixed 1024-entry block
    tnp = np.zeros(ntypes_pad, np.int64)
    tnr = np.zeros(ntypes_pad, np.int64)
    for i, t in enumerate(m.types):
        tnp[i], tnr[i] = len(t.params), len(t.results)
    fl = np.array([f.locals_n for f in m.functions], np.int64)
    fnp = np.array(
        [len(m.types[f.type_idx].params) for f in m.functions], np.int64
    )
    fnr = np.array(
        [len(m.types[f.type_idx].results) for f in m.functions], np.int64
    )
    inp = np.array(
        [len(m.types[ti].params) for _, _, ti in m.func_imports] or [0],
        np.int64,
    )
    inr = np.array(
        [len(m.types[ti].results) for _, _, ti in m.func_imports] or [0],
        np.int64,
    )
    pool = np.array(br_pool or [0], np.int64)
    return ins, np.array(func_off, np.int64), fl, fnp, fnr, tnp, tnr, \
        inp, inr, pool


class CInstance:
    """wasm_vm.Instance-compatible instance backed by the C engine."""

    def __init__(self, module: Module, host_funcs=None, memory_pages=2000,
                 max_pages=32768):
        lib = load_engine()
        self.m = module
        self.host = host_funcs or {}
        pages = module.mem_limits[0] if module.mem_limits else memory_pages
        if module.mem_import:
            pages = max(pages, memory_pages)
        mx = module.mem_limits[1] if module.mem_limits else None
        self.max_pages = min(mx, max_pages) if mx else max_pages
        self.max_pages = max(self.max_pages, pages)
        # anonymous mmap: 2 GB of ADDRESS SPACE, but pages are only backed
        # when touched — an instance costs what the module actually uses,
        # not max_pages (a create_string_buffer here zero-filled 256 MB
        # per WitnessCalculator)
        import mmap

        self._mm = mmap.mmap(-1, self.max_pages * PAGE)
        self.memory = memoryview(self._mm)
        self._membacking = (
            ctypes.c_uint8 * (self.max_pages * PAGE)
        ).from_buffer(self._mm)
        self._memptr = ctypes.cast(
            self._membacking, ctypes.POINTER(ctypes.c_uint8)
        )
        self._cur_pages = ctypes.c_int64(pages)
        self.n_imports = len(module.func_imports)

        glb = [int(v) for _, v in module.globals_init]
        self._globals = (ctypes.c_int64 * max(1, len(glb)))(*glb)
        table = list(module.tables[0]) if module.tables else []
        for off, idxs in module.elems:
            need = off + len(idxs)
            if len(table) < need:
                table.extend([None] * (need - len(table)))
            for k, fi in enumerate(idxs):
                table[off + k] = fi
        self._table = (ctypes.c_int64 * max(1, len(table)))(
            *[-1 if t is None else t for t in table]
        )
        for off, blob in module.datas:
            self.memory[off : off + len(blob)] = blob

        self._pending_exc = None

        def host_cb(idx, args_p, nargs, trap_p):
            mod, name, ti = module.func_imports[idx]
            fn = self.host.get((mod, name))
            try:
                if fn is None:
                    raise WasmTrap(f"unresolved import {mod}.{name}")
                args = [args_p[i] for i in range(nargs)]
                r = fn(*args)
                return (r or 0) & 0xFFFFFFFFFFFFFFFF
            except BaseException as e:  # noqa: BLE001 — carried across C
                self._pending_exc = e
                trap_p[0] = 1
                return 0

        self._host_cb = _HOSTFN(host_cb)  # keep a ref (GC safety)

        (ins, off, fl, fnp, fnr, tnp, tnr, inp, inr, pool) = _serialize(
            module
        )
        self._eng = lib.wx_new(
            np.ascontiguousarray(ins.reshape(-1)), ins.shape[0],
            off, len(module.functions),
            fl if len(fl) else np.zeros(0, np.int64),
            fnp if len(fnp) else np.zeros(0, np.int64),
            fnr if len(fnr) else np.zeros(0, np.int64),
            tnp, tnr, inp, inr, self.n_imports,
            pool, len(pool),
            self._table, len(self._table),
            self._globals,
            self._memptr,
            ctypes.byref(self._cur_pages), self.max_pages,
            self._host_cb,
        )
        if not self._eng:
            raise ImportError("wx_new failed")
        self._lib = lib
        if module.start_func is not None:
            self.call_index(module.start_func, [])

    def __del__(self):
        try:
            if getattr(self, "_eng", None):
                self._lib.wx_free(self._eng)
        except Exception:
            pass

    @property
    def globals(self):
        return list(self._globals)

    # -- Instance-compatible API -------------------------------------------

    def exported(self, name):
        kind, idx = self.m.exports[name]
        assert kind == 0
        return idx

    def call(self, name, args=()):
        return self.call_index(self.exported(name), list(args))

    def call_index(self, fi, args):
        if fi < self.n_imports:
            mod, name, ti = self.m.func_imports[fi]
            fn = self.host.get((mod, name))
            if fn is None:
                raise WasmTrap(f"unresolved import {mod}.{name}")
            res = fn(*args)
            nres = len(self.m.types[ti].results)
            return [] if nres == 0 else [res & 0xFFFFFFFF]
        abuf = (ctypes.c_uint64 * max(1, len(args)))(
            *[a & 0xFFFFFFFFFFFFFFFF for a in args]
        )
        rbuf = (ctypes.c_uint64 * 8)()
        nr = ctypes.c_int32(0)
        self._pending_exc = None
        rc = self._lib.wx_call(
            self._eng, fi, abuf, len(args), rbuf, ctypes.byref(nr)
        )
        if rc == 7:  # host exception carried across the C boundary
            exc = self._pending_exc or HostExit("unknown")
            self._pending_exc = None
            raise exc
        if rc == 8:
            raise WasmMemoryLimit(_TRAP_MSG[8])
        if rc != 0:
            raise WasmTrap(_TRAP_MSG.get(rc, f"trap code {rc}"))
        return [int(rbuf[i]) for i in range(nr.value)]
