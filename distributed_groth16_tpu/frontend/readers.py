"""Binary circuit-artifact readers: iden3 `.r1cs` and snarkjs `.wtns`.

Format parity with the reference's ark-circom readers
(ark-circom/src/circom/r1cs_reader.rs — iden3 r1cs_bin_format spec;
`.wtns` is the snarkjs witness container the same toolchain emits). Both are
little-endian section files: magic, version u32, n_sections u32, then
(type u32, size u64, payload) sections. Field elements are 32-byte LE
standard-form integers (BN254 only, as in the reference,
r1cs_reader.rs:163-189).

WASM witness calculation (the reference's wasmer-based WitnessCalculator,
ark-circom/src/witness/witness_calculator.rs) runs on the vendored
pure-Python interpreter (wasm_vm.py — no host WASM runtime ships in this
environment). Witnesses can also be supplied via `.wtns` files or the
native frontend (frontend/r1cs.py).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from ..ops.constants import R
from .r1cs import R1CS

_BN254_PRIME_LE = R.to_bytes(32, "little")


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def bytes(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("unexpected EOF")
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.bytes(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.bytes(8))[0]

    def field(self, n8: int = 32) -> int:
        return int.from_bytes(self.bytes(n8), "little")


def _sections(rd: _Reader, magic: bytes) -> dict[int, tuple[int, int]]:
    """Parse the container frame; returns {section_type: (offset, size)}."""
    if rd.bytes(4) != magic:
        raise ValueError(f"bad magic, expected {magic!r}")
    version = rd.u32()
    if version > 2:
        raise ValueError(f"unsupported version {version}")
    n_sections = rd.u32()
    out = {}
    for _ in range(n_sections):
        typ = rd.u32()
        size = rd.u64()
        out[typ] = (rd.pos, size)
        rd.pos += size
    return out


@dataclass
class R1CSHeader:
    n_wires: int
    n_pub_out: int
    n_pub_in: int
    n_prv_in: int
    n_labels: int
    n_constraints: int


def read_r1cs(path_or_bytes) -> tuple[R1CS, R1CSHeader]:
    """Parse an iden3 `.r1cs` file into the native R1CS struct.

    num_instance = 1 + n_pub_out + n_pub_in (wire 0 = constant 1), matching
    the reference (r1cs_reader.rs:29-31).
    """
    data = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    rd = _Reader(bytes(data))
    secs = _sections(rd, b"r1cs")
    # header (type 1)
    off, _ = secs[1]
    rd.pos = off
    n8 = rd.u32()
    if n8 != 32:
        raise ValueError("only 32-byte fields supported")
    prime = rd.bytes(32)
    if prime != _BN254_PRIME_LE:
        raise ValueError("only BN254 supported")
    hdr = R1CSHeader(
        n_wires=rd.u32(),
        n_pub_out=rd.u32(),
        n_pub_in=rd.u32(),
        n_prv_in=rd.u32(),
        n_labels=rd.u64(),
        n_constraints=rd.u32(),
    )
    # constraints (type 2): per constraint three LCs of (n u32, then
    # (wire u32, coeff 32B LE) entries)
    off, _ = secs[2]
    rd.pos = off

    def lc():
        n = rd.u32()
        out = []
        for _ in range(n):
            wire = rd.u32()
            coeff = rd.field(n8)
            out.append((coeff % R, wire))
        return out

    a_rows, b_rows, c_rows = [], [], []
    for _ in range(hdr.n_constraints):
        a_rows.append(lc())
        b_rows.append(lc())
        c_rows.append(lc())

    num_instance = 1 + hdr.n_pub_out + hdr.n_pub_in
    r1cs = R1CS(
        num_instance=num_instance,
        num_witness=hdr.n_wires - num_instance,
        a=a_rows,
        b=b_rows,
        c=c_rows,
    )
    return r1cs, hdr


def read_wtns(path_or_bytes) -> list[int]:
    """Parse a snarkjs `.wtns` witness file -> full assignment (wire order,
    starting with the constant 1)."""
    data = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    rd = _Reader(bytes(data))
    secs = _sections(rd, b"wtns")
    off, _ = secs[1]
    rd.pos = off
    n8 = rd.u32()
    prime = rd.field(n8)
    if prime != R:
        raise ValueError("only BN254 supported")
    n_witness = rd.u32()
    off, _ = secs[2]
    rd.pos = off
    return [rd.field(n8) for _ in range(n_witness)]


def write_r1cs(r1cs: R1CS, num_private_inputs: int | None = None) -> bytes:
    """Serialize a native R1CS to the iden3 `.r1cs` binary format — lets
    circuits built with frontend.r1cs.ConstraintSystem flow through every
    artifact path (service store, CLI) as standard files.

    num_private_inputs: the header's nPrvIn. The native ConstraintSystem
    does not distinguish private inputs from internal wires, so this
    defaults to num_witness (an over-count external iden3 tools will show);
    pass the true count for spec-exact headers.
    """
    import io

    def lc_bytes(lc):
        out = struct.pack("<I", len(lc))
        for coeff, wire in lc:
            out += struct.pack("<I", wire) + int(coeff).to_bytes(32, "little")
        return out

    header = struct.pack("<I", 32) + _BN254_PRIME_LE
    n_pub_out = 0
    n_pub_in = r1cs.num_instance - 1
    n_prv_in = (
        num_private_inputs
        if num_private_inputs is not None
        else r1cs.num_witness
    )
    header += struct.pack(
        "<IIIIQI",
        r1cs.num_wires,
        n_pub_out,
        n_pub_in,
        n_prv_in,
        r1cs.num_wires,
        r1cs.num_constraints,
    )
    constraints = b"".join(
        lc_bytes(r1cs.a[j]) + lc_bytes(r1cs.b[j]) + lc_bytes(r1cs.c[j])
        for j in range(r1cs.num_constraints)
    )
    wire_map = b"".join(
        struct.pack("<Q", i) for i in range(r1cs.num_wires)
    )
    buf = io.BytesIO()
    buf.write(b"r1cs" + struct.pack("<II", 1, 3))
    for typ, payload in ((1, header), (2, constraints), (3, wire_map)):
        buf.write(struct.pack("<IQ", typ, len(payload)))
        buf.write(payload)
    return buf.getvalue()


def write_wtns(assignment: list[int]) -> bytes:
    """Serialize a full assignment to the snarkjs `.wtns` binary format."""
    import io

    sec1 = struct.pack("<I", 32) + _BN254_PRIME_LE + struct.pack(
        "<I", len(assignment)
    )
    sec2 = b"".join(int(v % R).to_bytes(32, "little") for v in assignment)
    buf = io.BytesIO()
    buf.write(b"wtns" + struct.pack("<II", 2, 2))
    for typ, payload in ((1, sec1), (2, sec2)):
        buf.write(struct.pack("<IQ", typ, len(payload)))
        buf.write(payload)
    return buf.getvalue()


# Circom WASM witness calculation runs on the vendored pure-Python WASM
# interpreter (wasm_vm.py) — the wasmer role of witness_calculator.rs:17.
from .witness_calculator import WitnessCalculator  # noqa: E402,F401
