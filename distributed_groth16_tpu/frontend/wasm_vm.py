"""Minimal pure-Python WebAssembly interpreter for circom-emitted modules.

The reference executes circom `.wasm` witness generators under wasmer
(ark-circom/src/witness/witness_calculator.rs:56-153). No WASM runtime is
available in this image, so this module implements the small WASM subset
circom actually emits (verified by scanning every `.wasm` in the reference
checkout): integer-only MVP — i32/i64 arithmetic and comparisons, linear
memory with all integer load/store widths, structured control flow
(block/loop/if/br/br_if/br_table), direct and indirect calls, globals, and
imported host functions (`runtime.*` callbacks + optionally `env.memory`).
No floats, no SIMD, no reference types, no multi-value.

Design: function bodies are decoded once into flat instruction lists;
execution is a value-stack machine with an explicit control-frame stack
(frames record the branch-target pc, the value-stack height to unwind to,
and the block arity), which sidesteps static stack-height analysis while
staying faithful to structured-control semantics.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

__all__ = ["Module", "Instance", "WasmTrap", "HostExit"]

M32 = 0xFFFFFFFF
M64 = 0xFFFFFFFFFFFFFFFF
PAGE = 65536


class WasmTrap(RuntimeError):
    pass


class HostExit(RuntimeError):
    """Raised by host callbacks (runtime.exceptionHandler / runtime.error)."""

    def __init__(self, code):
        super().__init__(f"wasm runtime exception, code {code}")
        self.code = code


def _uleb(data, i):
    r = s = 0
    while True:
        b = data[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _sleb(data, i):
    r = s = 0
    while True:
        b = data[i]
        i += 1
        r |= (b & 0x7F) << s
        s += 7
        if not b & 0x80:
            if b & 0x40:
                r -= 1 << s
            return r, i


@dataclass
class FuncType:
    params: tuple
    results: tuple


@dataclass
class Function:
    type_idx: int
    locals_n: int = 0
    code: list = field(default_factory=list)  # flat (op, arg) list
    name: str = ""


# control-flow ops get structure metadata during pre-decode
_BLOCK, _LOOP, _IF = 0x02, 0x03, 0x04


class Module:
    """Parsed (and pre-decoded) WASM module."""

    def __init__(self, data: bytes):
        assert data[:8] == b"\x00asm\x01\x00\x00\x00", "bad wasm magic"
        self.types: list[FuncType] = []
        self.imports: list[tuple] = []  # (module, name, kind, extra)
        self.func_imports: list[tuple] = []
        self.functions: list[Function] = []
        self.tables: list[list] = []
        self.mem_limits = None  # (initial, max) if module defines memory
        self.mem_import = False
        self.globals_init: list[tuple] = []  # (mutable, init_value)
        self.exports: dict[str, tuple] = {}
        self.elems: list[tuple] = []  # (offset, [funcidx])
        self.datas: list[tuple] = []  # (offset, bytes)
        self.start_func: int | None = None
        self._parse(data)

    def _parse(self, data):
        i = 8
        code_bodies = []
        while i < len(data):
            sec, i = _uleb(data, i)
            size, i = _uleb(data, i)
            end = i + size
            j = i
            if sec == 1:  # types
                n, j = _uleb(data, j)
                for _ in range(n):
                    assert data[j] == 0x60
                    j += 1
                    np_, j = _uleb(data, j)
                    params = tuple(data[j : j + np_])
                    j += np_
                    nr, j = _uleb(data, j)
                    results = tuple(data[j : j + nr])
                    j += nr
                    self.types.append(FuncType(params, results))
            elif sec == 2:  # imports
                n, j = _uleb(data, j)
                for _ in range(n):
                    ml, j = _uleb(data, j)
                    mod = data[j : j + ml].decode()
                    j += ml
                    nl, j = _uleb(data, j)
                    name = data[j : j + nl].decode()
                    j += nl
                    kind = data[j]
                    j += 1
                    if kind == 0:  # function
                        ti, j = _uleb(data, j)
                        self.func_imports.append((mod, name, ti))
                    elif kind == 2:  # memory
                        flags = data[j]
                        j += 1
                        mn, j = _uleb(data, j)
                        mx = None
                        if flags & 1:
                            mx, j = _uleb(data, j)
                        self.mem_import = True
                        self.mem_limits = (mn, mx)
                    elif kind == 1:  # table
                        j += 1  # elemtype
                        flags = data[j]
                        j += 1
                        _, j = _uleb(data, j)
                        if flags & 1:
                            _, j = _uleb(data, j)
                    elif kind == 3:  # global
                        j += 2
                    self.imports.append((mod, name, kind))
            elif sec == 3:  # function decls
                n, j = _uleb(data, j)
                for _ in range(n):
                    ti, j = _uleb(data, j)
                    self.functions.append(Function(ti))
            elif sec == 4:  # tables
                n, j = _uleb(data, j)
                for _ in range(n):
                    j += 1  # elemtype 0x70
                    flags = data[j]
                    j += 1
                    mn, j = _uleb(data, j)
                    if flags & 1:
                        _, j = _uleb(data, j)
                    self.tables.append([None] * mn)
            elif sec == 5:  # memories
                n, j = _uleb(data, j)
                for _ in range(n):
                    flags = data[j]
                    j += 1
                    mn, j = _uleb(data, j)
                    mx = None
                    if flags & 1:
                        mx, j = _uleb(data, j)
                    self.mem_limits = (mn, mx)
            elif sec == 6:  # globals
                n, j = _uleb(data, j)
                for _ in range(n):
                    j += 1  # valtype
                    mut = data[j]
                    j += 1
                    val, j = self._const_expr(data, j)
                    self.globals_init.append((mut, val))
            elif sec == 7:  # exports
                n, j = _uleb(data, j)
                for _ in range(n):
                    nl, j = _uleb(data, j)
                    name = data[j : j + nl].decode()
                    j += nl
                    kind = data[j]
                    j += 1
                    idx, j = _uleb(data, j)
                    self.exports[name] = (kind, idx)
            elif sec == 8:  # start
                self.start_func, j = _uleb(data, j)
            elif sec == 9:  # elems
                n, j = _uleb(data, j)
                for _ in range(n):
                    flags, j = _uleb(data, j)
                    assert flags == 0, "only active funcref elems supported"
                    off, j = self._const_expr(data, j)
                    cnt, j = _uleb(data, j)
                    idxs = []
                    for _ in range(cnt):
                        fi, j = _uleb(data, j)
                        idxs.append(fi)
                    self.elems.append((off, idxs))
            elif sec == 10:  # code
                n, j = _uleb(data, j)
                for _ in range(n):
                    bsize, j = _uleb(data, j)
                    code_bodies.append((j, j + bsize))
                    j += bsize
            elif sec == 11:  # data
                n, j = _uleb(data, j)
                for _ in range(n):
                    flags, j = _uleb(data, j)
                    assert flags == 0, "only active data segments supported"
                    off, j = self._const_expr(data, j)
                    ln, j = _uleb(data, j)
                    self.datas.append((off, data[j : j + ln]))
                    j += ln
            i = end
        for fn, (s, e) in zip(self.functions, code_bodies):
            self._decode_body(fn, data, s, e)

    @staticmethod
    def _const_expr(data, j):
        op = data[j]
        j += 1
        if op == 0x41:
            v, j = _sleb(data, j)
        elif op == 0x42:
            v, j = _sleb(data, j)
        elif op == 0x23:
            v, j = _uleb(data, j)  # global.get — circom doesn't chain these
        else:
            raise WasmTrap(f"unsupported const expr opcode {op:#x}")
        assert data[j] == 0x0B
        return v, j + 1

    def _decode_body(self, fn: Function, data, j, end):
        nloc, j = _uleb(data, j)
        total = 0
        for _ in range(nloc):
            cnt, j = _uleb(data, j)
            j += 1
            total += cnt
        fn.locals_n = total
        code = []
        # control stack entries: [op, pc, else_pc] — patched on else/end
        ctrl = []
        while j < end:
            op = data[j]
            j += 1
            if op in (_BLOCK, _LOOP, _IF):
                bt, j = _sleb(data, j)
                arity = 0 if bt == -64 else 1  # 0x40 empty vs value type
                code.append([op, arity, -1, -1])  # [op, arity, end_pc, else_pc]
                ctrl.append(len(code) - 1)
            elif op == 0x05:  # else
                k = ctrl[-1]
                code.append([0x05, k, -1, -1])  # [2] patched to end_pc below
                code[k][3] = len(code)  # else body starts after the marker
            elif op == 0x0B:  # end
                if ctrl:
                    k = ctrl.pop()
                    code[k][2] = len(code)  # pc of this end instruction
                    if code[k][0] == _IF and code[k][3] != -1:
                        code[code[k][3] - 1][2] = len(code)  # else -> end
                    code.append([0x0B, k, -1, -1])
                else:
                    code.append([0x0B, -1, -1, -1])  # function end
            elif op in (0x0C, 0x0D):  # br / br_if
                depth, j = _uleb(data, j)
                code.append([op, depth, -1, -1])
            elif op == 0x0E:  # br_table
                cnt, j = _uleb(data, j)
                targets = []
                for _ in range(cnt):
                    d, j = _uleb(data, j)
                    targets.append(d)
                dflt, j = _uleb(data, j)
                code.append([op, targets, dflt, -1])
            elif op in (0x00, 0x01, 0x0F, 0x1A, 0x1B):  # unreachable/nop/ret/drop/select
                code.append([op, 0, -1, -1])
            elif op == 0x10:  # call
                fi, j = _uleb(data, j)
                code.append([op, fi, -1, -1])
            elif op == 0x11:  # call_indirect
                ti, j = _uleb(data, j)
                j += 1  # table byte
                code.append([op, ti, -1, -1])
            elif op in (0x20, 0x21, 0x22, 0x23, 0x24):  # local/global access
                idx, j = _uleb(data, j)
                code.append([op, idx, -1, -1])
            elif 0x28 <= op <= 0x3E:  # loads/stores
                _, j = _uleb(data, j)  # align
                off, j = _uleb(data, j)
                code.append([op, off, -1, -1])
            elif op in (0x3F, 0x40):  # memory.size / grow
                j += 1  # mem idx 0x00
                code.append([op, 0, -1, -1])
            elif op == 0x41:
                v, j = _sleb(data, j)
                code.append([op, v & M32, -1, -1])
            elif op == 0x42:
                v, j = _sleb(data, j)
                code.append([op, v & M64, -1, -1])
            else:
                code.append([op, 0, -1, -1])  # plain numeric op
        fn.code = code


class Instance:
    """An instantiated module: memory, globals, table, host imports.

    host_funcs: dict mapping (module, name) -> python callable.
    """

    def __init__(self, module: Module, host_funcs=None, memory_pages=2000):
        self.m = module
        self.host = host_funcs or {}
        pages = module.mem_limits[0] if module.mem_limits else memory_pages
        if module.mem_import:
            pages = max(pages, memory_pages)
        self.memory = bytearray(pages * PAGE)
        self.globals = [v for _, v in module.globals_init]
        self.table = list(module.tables[0]) if module.tables else []
        for off, idxs in module.elems:
            need = off + len(idxs)
            if len(self.table) < need:
                self.table.extend([None] * (need - len(self.table)))
            for k, fi in enumerate(idxs):
                self.table[off + k] = fi
        for off, blob in module.datas:
            self.memory[off : off + len(blob)] = blob
        self.n_imports = len(module.func_imports)
        if module.start_func is not None:
            self.call_index(module.start_func, [])

    # -- public API ---------------------------------------------------------

    def exported(self, name):
        kind, idx = self.m.exports[name]
        assert kind == 0
        return idx

    def call(self, name, args=()):
        return self.call_index(self.exported(name), list(args))

    def call_index(self, fi, args):
        if fi < self.n_imports:
            mod, name, ti = self.m.func_imports[fi]
            fn = self.host.get((mod, name))
            if fn is None:
                raise WasmTrap(f"unresolved import {mod}.{name}")
            res = fn(*args)
            nres = len(self.m.types[ti].results)
            return [] if nres == 0 else [res & M32]
        f = self.m.functions[fi - self.n_imports]
        ftype = self.m.types[f.type_idx]
        frame_locals = list(args) + [0] * f.locals_n
        result = self._exec(f, frame_locals)
        nres = len(ftype.results)
        return result[len(result) - nres :] if nres else []

    # -- interpreter core ---------------------------------------------------

    def _exec(self, f: Function, loc):
        code = f.code
        mem = self.memory
        stack = []
        # control frames: (is_loop, target_pc, stack_height, arity)
        frames = []
        pc = 0
        ncode = len(code)
        m = self.m

        def do_branch(depth, pc):
            if depth >= len(frames):
                # branch to the implicit function-level label: return from
                # the function (results are the top-of-stack values)
                frames.clear()
                return ncode
            for _ in range(depth):
                frames.pop()
            is_loop, target, height, arity = frames[-1]
            if is_loop:
                del stack[height:]
                return target
            vals = stack[len(stack) - arity :] if arity else []
            del stack[height:]
            stack.extend(vals)
            frames.pop()
            return target

        while pc < ncode:
            ins = code[pc]
            op = ins[0]
            pc += 1
            if op == 0x20:  # local.get
                stack.append(loc[ins[1]])
            elif op == 0x41 or op == 0x42:  # const
                stack.append(ins[1])
            elif op == 0x21:  # local.set
                loc[ins[1]] = stack.pop()
            elif op == 0x22:  # local.tee
                loc[ins[1]] = stack[-1]
            elif op == 0x28:  # i32.load
                a = stack[-1] + ins[1]
                stack[-1] = int.from_bytes(mem[a : a + 4], "little")
            elif op == 0x36:  # i32.store
                v = stack.pop()
                a = stack.pop() + ins[1]
                mem[a : a + 4] = v.to_bytes(4, "little")
            elif op == 0x29:  # i64.load
                a = stack[-1] + ins[1]
                stack[-1] = int.from_bytes(mem[a : a + 8], "little")
            elif op == 0x37:  # i64.store
                v = stack.pop()
                a = stack.pop() + ins[1]
                mem[a : a + 8] = v.to_bytes(8, "little")
            elif op == 0x6A:  # i32.add
                v = stack.pop()
                stack[-1] = (stack[-1] + v) & M32
            elif op == 0x7C:  # i64.add
                v = stack.pop()
                stack[-1] = (stack[-1] + v) & M64
            elif op == 0x02:  # block: branch target is after the end instr
                frames.append((False, ins[2] + 1, len(stack), ins[1]))
            elif op == 0x03:  # loop: branch target is the body start
                frames.append((True, pc, len(stack), 0))
            elif op == 0x04:  # if
                c = stack.pop()
                frames.append((False, ins[2] + 1, len(stack), ins[1]))
                if not c:
                    # jump to else body, or to the end instr (which pops)
                    pc = ins[3] if ins[3] != -1 else ins[2]
            elif op == 0x05:  # else marker: then-branch done, go to end instr
                pc = ins[2]
            elif op == 0x0B:  # end
                if ins[1] == -1:
                    return stack  # function-level end
                frames.pop()
            elif op == 0x0C:  # br
                pc = do_branch(ins[1], pc)
            elif op == 0x0D:  # br_if
                if stack.pop():
                    pc = do_branch(ins[1], pc)
            elif op == 0x0E:  # br_table
                k = stack.pop()
                targets, dflt = ins[1], ins[2]
                d = targets[k] if k < len(targets) else dflt
                pc = do_branch(d, pc)
            elif op == 0x0F:  # return
                return stack
            elif op == 0x10:  # call
                fi = ins[1]
                if fi < self.n_imports:
                    mod_, name, ti = m.func_imports[fi]
                    hf = self.host.get((mod_, name))
                    if hf is None:
                        raise WasmTrap(f"unresolved import {mod_}.{name}")
                    ftype = m.types[ti]
                    np_ = len(ftype.params)
                    args = stack[len(stack) - np_ :] if np_ else []
                    del stack[len(stack) - np_ :]
                    r = hf(*args)
                    if ftype.results:
                        stack.append(r & M32)
                else:
                    fn = m.functions[fi - self.n_imports]
                    ftype = m.types[fn.type_idx]
                    np_ = len(ftype.params)
                    args = stack[len(stack) - np_ :] if np_ else []
                    del stack[len(stack) - np_ :]
                    res = self._exec(fn, args + [0] * fn.locals_n)
                    nres = len(ftype.results)
                    if nres:
                        stack.extend(res[len(res) - nres :])
            elif op == 0x11:  # call_indirect
                k = stack.pop()
                if k >= len(self.table) or self.table[k] is None:
                    raise WasmTrap("undefined table element")
                fi = self.table[k]
                ftype = m.types[ins[1]]
                np_ = len(ftype.params)
                args = stack[len(stack) - np_ :] if np_ else []
                del stack[len(stack) - np_ :]
                res = self.call_index(fi, args)
                if ftype.results:
                    stack.extend(res[len(res) - len(ftype.results) :])
            elif op == 0x1A:  # drop
                stack.pop()
            elif op == 0x1B:  # select
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == 0x23:  # global.get
                stack.append(self.globals[ins[1]])
            elif op == 0x24:  # global.set
                self.globals[ins[1]] = stack.pop()
            elif op == 0x2C:  # i32.load8_s
                a = stack[-1] + ins[1]
                v = mem[a]
                stack[-1] = (v - 256 if v & 0x80 else v) & M32
            elif op == 0x2D:  # i32.load8_u
                stack[-1] = mem[stack[-1] + ins[1]]
            elif op == 0x2E:  # i32.load16_s
                a = stack[-1] + ins[1]
                v = int.from_bytes(mem[a : a + 2], "little")
                stack[-1] = (v - 65536 if v & 0x8000 else v) & M32
            elif op == 0x2F:  # i32.load16_u
                a = stack[-1] + ins[1]
                stack[-1] = int.from_bytes(mem[a : a + 2], "little")
            elif op == 0x30:  # i64.load8_s
                a = stack[-1] + ins[1]
                v = mem[a]
                stack[-1] = (v - 256 if v & 0x80 else v) & M64
            elif op == 0x31:  # i64.load8_u
                stack[-1] = mem[stack[-1] + ins[1]]
            elif op == 0x32:  # i64.load16_s
                a = stack[-1] + ins[1]
                v = int.from_bytes(mem[a : a + 2], "little")
                stack[-1] = (v - 65536 if v & 0x8000 else v) & M64
            elif op == 0x33:  # i64.load16_u
                a = stack[-1] + ins[1]
                stack[-1] = int.from_bytes(mem[a : a + 2], "little")
            elif op == 0x34:  # i64.load32_s
                a = stack[-1] + ins[1]
                v = int.from_bytes(mem[a : a + 4], "little")
                stack[-1] = (v - (1 << 32) if v & 0x80000000 else v) & M64
            elif op == 0x35:  # i64.load32_u
                a = stack[-1] + ins[1]
                stack[-1] = int.from_bytes(mem[a : a + 4], "little")
            elif op == 0x38 or op == 0x39:
                raise WasmTrap("floats unsupported")
            elif op == 0x3A:  # i32.store8
                v = stack.pop()
                mem[stack.pop() + ins[1]] = v & 0xFF
            elif op == 0x3B:  # i32.store16
                v = stack.pop()
                a = stack.pop() + ins[1]
                mem[a : a + 2] = (v & 0xFFFF).to_bytes(2, "little")
            elif op == 0x3C:  # i64.store8
                v = stack.pop()
                mem[stack.pop() + ins[1]] = v & 0xFF
            elif op == 0x3D:  # i64.store16
                v = stack.pop()
                a = stack.pop() + ins[1]
                mem[a : a + 2] = (v & 0xFFFF).to_bytes(2, "little")
            elif op == 0x3E:  # i64.store32
                v = stack.pop()
                a = stack.pop() + ins[1]
                mem[a : a + 4] = (v & M32).to_bytes(4, "little")
            elif op == 0x3F:  # memory.size
                stack.append(len(mem) // PAGE)
            elif op == 0x40:  # memory.grow
                delta = stack.pop()
                old = len(mem) // PAGE
                self.memory.extend(bytes(delta * PAGE))
                mem = self.memory
                stack.append(old)
            elif op == 0x45:  # i32.eqz
                stack[-1] = 1 if stack[-1] == 0 else 0
            elif op == 0x46:  # i32.eq
                v = stack.pop()
                stack[-1] = 1 if stack[-1] == v else 0
            elif op == 0x47:  # i32.ne
                v = stack.pop()
                stack[-1] = 1 if stack[-1] != v else 0
            elif op == 0x48:  # i32.lt_s
                v = _s32(stack.pop())
                stack[-1] = 1 if _s32(stack[-1]) < v else 0
            elif op == 0x49:  # i32.lt_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] < v else 0
            elif op == 0x4A:  # i32.gt_s
                v = _s32(stack.pop())
                stack[-1] = 1 if _s32(stack[-1]) > v else 0
            elif op == 0x4B:  # i32.gt_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] > v else 0
            elif op == 0x4C:  # i32.le_s
                v = _s32(stack.pop())
                stack[-1] = 1 if _s32(stack[-1]) <= v else 0
            elif op == 0x4D:  # i32.le_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] <= v else 0
            elif op == 0x4E:  # i32.ge_s
                v = _s32(stack.pop())
                stack[-1] = 1 if _s32(stack[-1]) >= v else 0
            elif op == 0x4F:  # i32.ge_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] >= v else 0
            elif op == 0x50:  # i64.eqz
                stack[-1] = 1 if stack[-1] == 0 else 0
            elif op == 0x51:  # i64.eq
                v = stack.pop()
                stack[-1] = 1 if stack[-1] == v else 0
            elif op == 0x52:  # i64.ne
                v = stack.pop()
                stack[-1] = 1 if stack[-1] != v else 0
            elif op == 0x53:  # i64.lt_s
                v = _s64(stack.pop())
                stack[-1] = 1 if _s64(stack[-1]) < v else 0
            elif op == 0x54:  # i64.lt_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] < v else 0
            elif op == 0x55:  # i64.gt_s
                v = _s64(stack.pop())
                stack[-1] = 1 if _s64(stack[-1]) > v else 0
            elif op == 0x56:  # i64.gt_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] > v else 0
            elif op == 0x57:  # i64.le_s
                v = _s64(stack.pop())
                stack[-1] = 1 if _s64(stack[-1]) <= v else 0
            elif op == 0x58:  # i64.le_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] <= v else 0
            elif op == 0x59:  # i64.ge_s
                v = _s64(stack.pop())
                stack[-1] = 1 if _s64(stack[-1]) >= v else 0
            elif op == 0x5A:  # i64.ge_u
                v = stack.pop()
                stack[-1] = 1 if stack[-1] >= v else 0
            elif op == 0x67:  # i32.clz
                v = stack[-1]
                stack[-1] = 32 - v.bit_length() if v else 32
            elif op == 0x68:  # i32.ctz
                v = stack[-1]
                stack[-1] = (v & -v).bit_length() - 1 if v else 32
            elif op == 0x69:  # i32.popcnt
                stack[-1] = bin(stack[-1]).count("1")
            elif op == 0x6B:  # i32.sub
                v = stack.pop()
                stack[-1] = (stack[-1] - v) & M32
            elif op == 0x6C:  # i32.mul
                v = stack.pop()
                stack[-1] = (stack[-1] * v) & M32
            elif op == 0x6D:  # i32.div_s
                v = _s32(stack.pop())
                a = _s32(stack[-1])
                if v == 0:
                    raise WasmTrap("division by zero")
                if a == -(1 << 31) and v == -1:
                    raise WasmTrap("integer overflow")
                stack[-1] = _idiv_trunc(a, v) & M32
            elif op == 0x6E:  # i32.div_u
                v = stack.pop()
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = stack[-1] // v
            elif op == 0x6F:  # i32.rem_s
                v = _s32(stack.pop())
                a = _s32(stack[-1])
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = (a - _idiv_trunc(a, v) * v) & M32
            elif op == 0x70:  # i32.rem_u
                v = stack.pop()
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = stack[-1] % v
            elif op == 0x71:  # i32.and
                v = stack.pop()
                stack[-1] &= v
            elif op == 0x72:  # i32.or
                v = stack.pop()
                stack[-1] |= v
            elif op == 0x73:  # i32.xor
                v = stack.pop()
                stack[-1] ^= v
            elif op == 0x74:  # i32.shl
                v = stack.pop() & 31
                stack[-1] = (stack[-1] << v) & M32
            elif op == 0x75:  # i32.shr_s
                v = stack.pop() & 31
                stack[-1] = (_s32(stack[-1]) >> v) & M32
            elif op == 0x76:  # i32.shr_u
                v = stack.pop() & 31
                stack[-1] >>= v
            elif op == 0x77:  # i32.rotl
                v = stack.pop() & 31
                a = stack[-1]
                stack[-1] = ((a << v) | (a >> (32 - v))) & M32 if v else a
            elif op == 0x78:  # i32.rotr
                v = stack.pop() & 31
                a = stack[-1]
                stack[-1] = ((a >> v) | (a << (32 - v))) & M32 if v else a
            elif op == 0x79:  # i64.clz
                v = stack[-1]
                stack[-1] = 64 - v.bit_length() if v else 64
            elif op == 0x7A:  # i64.ctz
                v = stack[-1]
                stack[-1] = (v & -v).bit_length() - 1 if v else 64
            elif op == 0x7B:  # i64.popcnt
                stack[-1] = bin(stack[-1]).count("1")
            elif op == 0x7D:  # i64.sub
                v = stack.pop()
                stack[-1] = (stack[-1] - v) & M64
            elif op == 0x7E:  # i64.mul
                v = stack.pop()
                stack[-1] = (stack[-1] * v) & M64
            elif op == 0x7F:  # i64.div_s
                v = _s64(stack.pop())
                a = _s64(stack[-1])
                if v == 0:
                    raise WasmTrap("division by zero")
                if a == -(1 << 63) and v == -1:
                    raise WasmTrap("integer overflow")
                stack[-1] = _idiv_trunc(a, v) & M64
            elif op == 0x80:  # i64.div_u
                v = stack.pop()
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = stack[-1] // v
            elif op == 0x81:  # i64.rem_s
                v = _s64(stack.pop())
                a = _s64(stack[-1])
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = (a - _idiv_trunc(a, v) * v) & M64
            elif op == 0x82:  # i64.rem_u
                v = stack.pop()
                if v == 0:
                    raise WasmTrap("division by zero")
                stack[-1] = stack[-1] % v
            elif op == 0x83:  # i64.and
                v = stack.pop()
                stack[-1] &= v
            elif op == 0x84:  # i64.or
                v = stack.pop()
                stack[-1] |= v
            elif op == 0x85:  # i64.xor
                v = stack.pop()
                stack[-1] ^= v
            elif op == 0x86:  # i64.shl
                v = stack.pop() & 63
                stack[-1] = (stack[-1] << v) & M64
            elif op == 0x87:  # i64.shr_s
                v = stack.pop() & 63
                stack[-1] = (_s64(stack[-1]) >> v) & M64
            elif op == 0x88:  # i64.shr_u
                v = stack.pop() & 63
                stack[-1] >>= v
            elif op == 0xA7:  # i32.wrap_i64
                stack[-1] &= M32
            elif op == 0xAC:  # i64.extend_i32_s
                stack[-1] = _s32(stack[-1]) & M64
            elif op == 0xAD:  # i64.extend_i32_u
                pass  # stored unsigned already
            elif op == 0x00:  # unreachable
                raise WasmTrap("unreachable")
            elif op == 0x01:  # nop
                pass
            else:
                raise WasmTrap(f"unsupported opcode {op:#x}")
        return stack



def _idiv_trunc(a: int, v: int) -> int:
    """Truncating (toward-zero) signed integer division — exact for the
    full i64 range (float-based int(a / v) loses precision above 2^53)."""
    q = abs(a) // abs(v)
    return -q if (a < 0) != (v < 0) else q

def _s32(v):
    return v - 0x100000000 if v & 0x80000000 else v


def _s64(v):
    return v - 0x10000000000000000 if v & 0x8000000000000000 else v
