"""Native R1CS representation and constraint-system builder.

The TPU build owns the constraint-system algebra natively (the reference
leans on the forked ark-relations ConstraintSystem; the observable surface
is ConstraintMatrices: num_instance_variables, num_constraints, and sparse
A/B/C rows of (coeff, wire) pairs — groth16/src/qap.rs:44-91 consumes
exactly that). Wire convention (arkworks/circom): wire 0 is the constant 1,
wires 1..num_instance are public inputs, the rest are private witness.

`ConstraintSystem` is the Python circuit-writing frontend (the role arkworks'
ConstraintSynthesizer plays for the reference's test circuits); `R1CS` is the
interchange struct shared with the .r1cs file reader (frontend/readers.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..ops.constants import R

# A linear combination is a list of (coeff, wire) pairs; coeff is an int mod r.
LinearCombination = list[tuple[int, int]]


@dataclass
class R1CS:
    """Sparse R1CS: for every constraint j, <A_j, z> * <B_j, z> == <C_j, z>
    where z = [1, public..., private...]."""

    num_instance: int  # includes the constant-1 wire 0
    num_witness: int
    a: list[LinearCombination]
    b: list[LinearCombination]
    c: list[LinearCombination]

    @property
    def num_constraints(self) -> int:
        return len(self.a)

    @property
    def num_wires(self) -> int:
        return self.num_instance + self.num_witness

    def eval_lc(self, lc: LinearCombination, z: list[int]) -> int:
        return sum(coeff * z[wire] for coeff, wire in lc) % R

    def is_satisfied(self, z: list[int]) -> bool:
        if len(z) != self.num_wires or z[0] != 1:
            return False
        for aj, bj, cj in zip(self.a, self.b, self.c):
            if self.eval_lc(aj, z) * self.eval_lc(bj, z) % R != self.eval_lc(
                cj, z
            ):
                return False
        return True


@dataclass
class ConstraintSystem:
    """Imperative circuit builder producing an R1CS + full assignment.

    Usage:
        cs = ConstraintSystem()
        x = cs.new_instance(3)
        y = cs.new_witness(9)
        cs.enforce([(1, x)], [(1, x)], [(1, y)])   # x * x == y
        r1cs, assignment = cs.finish()
    """

    instance: list[int] = field(default_factory=lambda: [1])
    witness: list[int] = field(default_factory=list)
    a: list[LinearCombination] = field(default_factory=list)
    b: list[LinearCombination] = field(default_factory=list)
    c: list[LinearCombination] = field(default_factory=list)
    _finished: bool = False

    ONE = 0  # wire index of the constant 1

    def new_instance(self, value: int) -> int:
        assert not self._finished, "instance wires must precede finish()"
        assert not self.witness, "allocate all instance wires before witness"
        self.instance.append(value % R)
        return len(self.instance) - 1

    def new_witness(self, value: int) -> int:
        assert not self._finished
        self.witness.append(value % R)
        return len(self.instance) + len(self.witness) - 1

    def enforce(
        self, a: LinearCombination, b: LinearCombination, c: LinearCombination
    ) -> None:
        self.a.append([(int(co) % R, w) for co, w in a])
        self.b.append([(int(co) % R, w) for co, w in b])
        self.c.append([(int(co) % R, w) for co, w in c])

    # convenience gadgets ----------------------------------------------------

    def mul(self, x: int, y: int) -> int:
        """Allocate z = x * y with its constraint; returns the wire."""
        z = self.new_witness(self.value(x) * self.value(y) % R)
        self.enforce([(1, x)], [(1, y)], [(1, z)])
        return z

    def add_const(self, x: int, k: int) -> int:
        """Allocate z = x + k (one constraint via multiplication by 1)."""
        z = self.new_witness((self.value(x) + k) % R)
        self.enforce([(1, x), (k % R, self.ONE)], [(1, self.ONE)], [(1, z)])
        return z

    def enforce_equal_const(self, x: int, k: int) -> None:
        self.enforce([(1, x)], [(1, self.ONE)], [(k % R, self.ONE)])

    def value(self, wire: int) -> int:
        ni = len(self.instance)
        return self.instance[wire] if wire < ni else self.witness[wire - ni]

    def finish(self) -> tuple[R1CS, list[int]]:
        self._finished = True
        r1cs = R1CS(
            num_instance=len(self.instance),
            num_witness=len(self.witness),
            a=self.a,
            b=self.b,
            c=self.c,
        )
        assignment = self.instance + self.witness
        assert r1cs.is_satisfied(assignment), "circuit is not satisfied"
        return r1cs, assignment


def mult_chain_circuit(x0: int, length: int) -> ConstraintSystem:
    """The fixtures/million-style chain: x_{i+1} = x_i * x_i + x_i, public
    output = final value (fixtures/million/million.circom shape — a long
    multiplicative chain whose constraint count is `length`)."""
    # compute final value first so it can be an instance wire (instance
    # wires must be allocated before witness wires)
    acc = x0 % R
    for _ in range(length):
        acc = (acc * acc + acc) % R
    cs = ConstraintSystem()
    out = cs.new_instance(acc)
    x = cs.new_witness(x0)
    for i in range(length):
        v = cs.value(x)
        nxt = (v * v + v) % R
        if i == length - 1:
            cs.enforce([(1, x)], [(1, x)], [(1, out), (R - 1, x)])
        else:
            y = cs.new_witness(nxt)
            cs.enforce([(1, x)], [(1, x)], [(1, y), (R - 1, x)])
            x = y
    return cs
