"""snarkjs JSON interop: parse `proof.json` / `public.json` /
`verification_key.json` into this framework's host types.

This is the external differential surface: a proof produced by snarkjs (an
independent Groth16 implementation) must verify under our pairing stack,
which is the role the reference's `ark-circom/tests/groth16.rs:1-109` and
`test-vectors/prove.sh` pipeline play for arkworks.

snarkjs point encoding: decimal strings, projective with an explicit z
coordinate — G1 as [x, y, z], G2 as [[x0, x1], [y0, y1], [z0, z1]] with
each Fq2 element listed as [c0, c1]. z == 0 encodes infinity; z is
otherwise almost always 1, but we normalize generally.
"""

from __future__ import annotations

import json

from ..models.groth16.keys import Proof, VerifyingKey
from ..ops import refmath as rm
from ..ops.constants import Q, R


def _mul_unreduced(ops, p, k: int):
    """Double-and-add WITHOUT reducing k mod the group order — unlike
    _CurveOps.scalar_mul, so [r]P is a meaningful subgroup test."""
    acc, base = None, p
    while k:
        if k & 1:
            acc = ops.add(acc, base)
        base = ops.double(base)
        k >>= 1
    return acc


def _g1_from_json(coords) -> tuple | None:
    x, y, z = (int(c) % Q for c in coords)
    if z == 0:
        return None
    if z != 1:
        zinv = rm.finv(z, Q)
        x, y = x * zinv % Q, y * zinv % Q
    pt = (x, y)
    if not rm.G1.is_on_curve(pt):
        raise ValueError("snarkjs G1 point not on curve")
    return pt


def _fq2_from_json(pair) -> tuple:
    return (int(pair[0]) % Q, int(pair[1]) % Q)


def _g2_from_json(coords) -> tuple | None:
    x, y, z = (_fq2_from_json(c) for c in coords)
    if z == (0, 0):
        return None
    if z != (1, 0):
        zinv = rm.fq2_inv(z)
        x, y = rm.fq2_mul(x, zinv), rm.fq2_mul(y, zinv)
    pt = (x, y)
    if not rm.G2.is_on_curve(pt):
        raise ValueError("snarkjs G2 point not on curve")
    # BN254 G2 has a large cofactor: on-curve does NOT imply prime-order.
    # Without this, a crafted proof/vk can smuggle a small-subgroup point
    # into the pairing (arkworks/snarkjs both reject at deserialization).
    if _mul_unreduced(rm.G2, pt, R) is not None:
        raise ValueError("snarkjs G2 point not in the r-order subgroup")
    return pt


def _load(path_or_obj):
    if isinstance(path_or_obj, (dict, list)):
        return path_or_obj
    with open(path_or_obj) as f:
        return json.load(f)


def load_proof(path_or_obj) -> Proof:
    """Parse a snarkjs `proof.json` (groth16 / bn128 only)."""
    obj = _load(path_or_obj)
    if obj.get("protocol", "groth16") != "groth16":
        raise ValueError(f"unsupported protocol {obj['protocol']!r}")
    return Proof(
        a=_g1_from_json(obj["pi_a"]),
        b=_g2_from_json(obj["pi_b"]),
        c=_g1_from_json(obj["pi_c"]),
    )


def load_public(path_or_obj) -> list[int]:
    """Parse a snarkjs `public.json` (list of decimal field strings)."""
    return [int(s) for s in _load(path_or_obj)]


def load_verification_key(path_or_obj) -> VerifyingKey:
    """Parse a snarkjs `verification_key.json`.

    Ignores `vk_alphabeta_12` (a precomputed pairing snarkjs carries as an
    optimization); our verifier recomputes e(alpha, beta) inside the single
    multi-pairing check.
    """
    obj = _load(path_or_obj)
    if obj.get("protocol") != "groth16":
        raise ValueError(f"unsupported protocol {obj.get('protocol')!r}")
    if obj.get("curve") not in ("bn128", "bn254", None):
        raise ValueError(f"unsupported curve {obj.get('curve')!r}")
    return VerifyingKey(
        alpha_g1=_g1_from_json(obj["vk_alpha_1"]),
        beta_g2=_g2_from_json(obj["vk_beta_2"]),
        gamma_g2=_g2_from_json(obj["vk_gamma_2"]),
        delta_g2=_g2_from_json(obj["vk_delta_2"]),
        gamma_abc_g1=[_g1_from_json(p) for p in obj["IC"]],
    )


def _g1_to_json(pt) -> list[str]:
    if pt is None:
        return ["0", "1", "0"]
    return [str(pt[0]), str(pt[1]), "1"]


def _g2_to_json(pt) -> list[list[str]]:
    if pt is None:
        return [["0", "0"], ["1", "0"], ["0", "0"]]
    (x0, x1), (y0, y1) = pt
    return [[str(x0), str(x1)], [str(y0), str(y1)], ["1", "0"]]


def dump_proof(proof: Proof) -> dict:
    """Emit the snarkjs `proof.json` shape (round-trips with load_proof)."""
    return {
        "pi_a": _g1_to_json(proof.a),
        "pi_b": _g2_to_json(proof.b),
        "pi_c": _g1_to_json(proof.c),
        "protocol": "groth16",
        "curve": "bn128",
    }
