"""One-call circuit facade: load a Circom (.wasm, .r1cs) pair, push
inputs, get a witness-populated circuit — the ergonomic front door the
reference exposes as CircomConfig/CircomBuilder
(ark-circom/src/circom/builder.rs:20-97).

    cfg = CircomConfig("circuit.wasm", "circuit.r1cs")
    b = CircomBuilder(cfg)
    b.push_input("a", 3)
    circuit = b.build()            # witness computed + (optionally) checked
    pk = setup(circuit.r1cs)       # models/groth16 setup
    proof = prove_single(pk, CompiledR1CS(circuit.r1cs),
                         fr().encode(circuit.witness))
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .r1cs import R1CS
from .readers import read_r1cs
from .witness_calculator import WitnessCalculator


@dataclass
class CircomCircuit:
    """An R1CS plus (optionally) its computed witness — the builder's
    product (builder.rs CircomCircuit). `witness` is a flat list of ints
    (wire 0 = the constant 1) or None for the setup-only circuit."""

    r1cs: R1CS
    witness: list[int] | None = None

    def public_inputs(self) -> list[int]:
        """The instance wires (excluding the constant wire), as the
        verifier consumes them."""
        if self.witness is None:
            raise ValueError("no witness set — call CircomBuilder.build()")
        return self.witness[1 : self.r1cs.num_instance]


class CircomConfig:
    """Loaded (witness calculator, R1CS) pair (builder.rs:26-37).

    sanity_check=True makes build() verify the witness against every
    constraint (the reference runs this as a debug_assert)."""

    def __init__(self, wasm_path: str, r1cs_path: str,
                 sanity_check: bool = False):
        self.wtns = WitnessCalculator.from_file(wasm_path)
        self.r1cs, _ = read_r1cs(r1cs_path)
        self.sanity_check = sanity_check


@dataclass
class CircomBuilder:
    """Accumulates named inputs, then builds the witness-populated circuit
    (builder.rs:39-100). push_input may be called repeatedly with the
    same name to build array inputs, matching the reference's
    Vec-per-name semantics."""

    cfg: CircomConfig
    inputs: dict = field(default_factory=dict)

    def push_input(self, name: str, value) -> None:
        self.inputs.setdefault(name, []).append(int(value))

    def setup(self) -> CircomCircuit:
        """Witness-less circuit for parameter generation (builder.rs:57-68)."""
        return CircomCircuit(r1cs=self.cfg.r1cs)

    def build(self) -> CircomCircuit:
        """Compute the witness for the pushed inputs and attach it
        (builder.rs:70-100). The calculator accepts the per-name lists
        directly (the reference's Vec<BigInt> semantics)."""
        witness = self.cfg.wtns.calculate_witness(self.inputs)
        circuit = CircomCircuit(r1cs=self.cfg.r1cs, witness=witness)
        if self.cfg.sanity_check and not self.cfg.r1cs.is_satisfied(witness):
            raise ValueError("witness does not satisfy the R1CS")
        return circuit
