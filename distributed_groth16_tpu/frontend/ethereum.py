"""Ethereum/Solidity export: proofs, verifying keys, and public inputs as
the uint256 tuples Groth16 verifier contracts expect.

Role parity with the reference's ethereum.rs (ark-circom/src/ethereum.rs:
10-174): G1 -> (x, y), G2 -> ([x.c1, x.c0], [y.c1, y.c0]) — Solidity
pairing precompiles take the Fq2 c1 limb FIRST (ethereum.rs:82-85) —
infinity as all-zero coordinates, and field elements as uint256 integers
(decimal strings in the snarkjs-style JSON, 0x-words in calldata).
Conversions are bijective: from_* functions accept
the exported form back into the native Proof / VerifyingKey types.
"""

from __future__ import annotations

from ..models.groth16.keys import Proof, VerifyingKey
from ..ops.constants import Q, R


def _g1_tuple(pt) -> tuple[int, int]:
    if pt is None:
        return (0, 0)
    x, y = pt
    return (int(x) % Q, int(y) % Q)


def _g2_tuple(pt) -> tuple[tuple[int, int], tuple[int, int]]:
    """c1 limb serialized first (ethereum.rs:82-85)."""
    if pt is None:
        return ((0, 0), (0, 0))
    (x0, x1), (y0, y1) = pt
    return ((int(x1) % Q, int(x0) % Q), (int(y1) % Q, int(y0) % Q))


def _g1_from_tuple(t):
    x, y = t
    if x == 0 and y == 0:
        return None
    return (x % Q, y % Q)


def _g2_from_tuple(t):
    (x1, x0), (y1, y0) = t
    if x0 == x1 == y0 == y1 == 0:
        return None
    return ((x0 % Q, x1 % Q), (y0 % Q, y1 % Q))


def proof_to_eth(proof: Proof):
    """(a, b, c) uint256 tuples — the calldata layout of a Solidity
    Groth16 verifier's verifyProof (ethereum.rs Proof::as_tuple)."""
    return (
        _g1_tuple(proof.a),
        _g2_tuple(proof.b),
        _g1_tuple(proof.c),
    )


def proof_from_eth(t) -> Proof:
    a, b, c = t
    return Proof(a=_g1_from_tuple(a), b=_g2_from_tuple(b), c=_g1_from_tuple(c))


def vk_to_eth(vk: VerifyingKey):
    """(alpha1, beta2, gamma2, delta2, ic) uint256 tuples
    (ethereum.rs VerifyingKey::as_tuple)."""
    return (
        _g1_tuple(vk.alpha_g1),
        _g2_tuple(vk.beta_g2),
        _g2_tuple(vk.gamma_g2),
        _g2_tuple(vk.delta_g2),
        [_g1_tuple(p) for p in vk.gamma_abc_g1],
    )


def vk_from_eth(t) -> VerifyingKey:
    alpha, beta, gamma, delta, ic = t
    return VerifyingKey(
        alpha_g1=_g1_from_tuple(alpha),
        beta_g2=_g2_from_tuple(beta),
        gamma_g2=_g2_from_tuple(gamma),
        delta_g2=_g2_from_tuple(delta),
        gamma_abc_g1=[_g1_from_tuple(p) for p in ic],
    )


def inputs_to_eth(values) -> list[int]:
    """Public inputs as uint256 ints (ethereum.rs Inputs)."""
    return [int(v) % R for v in values]


# -- snarkjs-style JSON forms ------------------------------------------------


def proof_to_json(proof: Proof) -> dict:
    """snarkjs-compatible proof JSON (pi_a/pi_b/pi_c, projective with
    z = 1; pi_b rows keep snarkjs' c0-first JSON order). Delegates to
    frontend.snarkjs.dump_proof — one emitter for the external format."""
    from .snarkjs import dump_proof

    return dump_proof(proof)


def solidity_calldata(proof: Proof, public_inputs) -> str:
    """The exact string `snarkjs generatecall` emits: four bracketed
    groups joined by bare commas with NO enclosing outer brackets —
    `[A.x, A.y],[[B.x.c1, B.x.c0],[B.y.c1, B.y.c0]],[C.x, C.y],[inputs]`
    — each word a quoted 0x-padded 32-byte hex, a space after the comma
    inside the 2-element pairs, none between inputs (snarkjs
    groth16ExportSolidityCallData). Paste-compatible with Remix /
    verifier tooling expecting generatecall output."""

    def word(v: int) -> str:
        return '"0x' + int(v).to_bytes(32, "big").hex() + '"'

    a, b, c = proof_to_eth(proof)
    inputs = ",".join(word(v) for v in inputs_to_eth(public_inputs))
    return (
        f"[{word(a[0])}, {word(a[1])}],"
        f"[[{word(b[0][0])}, {word(b[0][1])}],"
        f"[{word(b[1][0])}, {word(b[1][1])}]],"
        f"[{word(c[0])}, {word(c[1])}],"
        f"[{inputs}]"
    )
