"""Arkworks-style compressed point (de)serialization for the wire format.

The reference's service DTOs carry proofs as ark-serialize compressed bytes
(common/src/utils/serializer.rs ark_se/ark_de). Convention implemented here
(ark-serialize 0.4 short-Weierstrass compressed):

  * G1: 32 bytes — x little-endian, flags in the top 2 bits of the LAST
    byte. G2: 64 bytes — x = c0 || c1 little-endian, flags likewise.
  * flags: 0x40 = point at infinity (x serialized as 0);
           0x80 = y is the lexicographically "negative" (larger) root;
           0x00 = smaller root.
  * proof = a (G1) || b (G2) || c (G1) = 128 bytes.

Decompression recovers y by square root (BN254: q ≡ 3 mod 4, so
sqrt = x^((q+1)/4) in Fq; Fq2 via the complex-norm method) and picks the
root per the flag.
"""

from __future__ import annotations

from ..ops.constants import G1_B, G2_B, Q
from ..ops.refmath import fq2_mul, fq2_sq, fq2_add
from ..models.groth16.keys import Proof

_HALF = (Q - 1) // 2


def _is_neg(y: int) -> bool:
    """'negative' = the larger of {y, -y} (y > q/2)."""
    return y > _HALF


def _fq2_is_neg(y) -> bool:
    """Lexicographic on (c1, c0): larger root flagged."""
    c0, c1 = y
    if c1 != 0:
        return _is_neg(c1)
    return _is_neg(c0)


def _sqrt_fq(a: int) -> int | None:
    r = pow(a, (Q + 1) // 4, Q)
    return r if r * r % Q == a else None


def _sqrt_fq2(a) -> tuple | None:
    a0, a1 = a[0] % Q, a[1] % Q
    if a1 == 0:
        r = _sqrt_fq(a0)
        if r is not None:
            return (r, 0)
        # sqrt of a non-residue lands in the u-axis: a0 = -(x1^2)
        r = _sqrt_fq((-a0) % Q)
        return None if r is None else (0, r)
    norm = (a0 * a0 + a1 * a1) % Q
    n = _sqrt_fq(norm)
    if n is None:
        return None
    inv2 = pow(2, Q - 2, Q)
    for sign in (1, -1):
        t = (a0 + sign * n) % Q * inv2 % Q
        x0 = _sqrt_fq(t)
        if x0 is None or x0 == 0:
            continue
        x1 = a1 * pow(2 * x0 % Q, Q - 2, Q) % Q
        if fq2_sq((x0, x1)) == (a0, a1):
            return (x0, x1)
    return None


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(32)
        out[-1] = 0x40
        return bytes(out)
    x, y = pt
    out = bytearray(int(x).to_bytes(32, "little"))
    if _is_neg(y):
        out[-1] |= 0x80
    return bytes(out)


def g1_from_bytes(b: bytes):
    assert len(b) == 32
    flags = b[31] & 0xC0
    x = int.from_bytes(bytes(b[:31]) + bytes([b[31] & 0x3F]), "little")
    if flags & 0x40:
        return None
    if x >= Q:
        raise ValueError("G1 x coordinate out of range")
    y2 = (pow(x, 3, Q) + G1_B) % Q
    y = _sqrt_fq(y2)
    if y is None:
        raise ValueError("not a point on G1")
    if bool(flags & 0x80) != _is_neg(y):
        y = (Q - y) % Q
    return (x, y)  # G1 cofactor is 1: on-curve == in-subgroup


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(64)
        out[-1] = 0x40
        return bytes(out)
    (x0, x1), y = pt
    out = bytearray(
        int(x0).to_bytes(32, "little") + int(x1).to_bytes(32, "little")
    )
    if _fq2_is_neg(y):
        out[-1] |= 0x80
    return bytes(out)


def g2_from_bytes(b: bytes):
    assert len(b) == 64
    flags = b[63] & 0xC0
    x0 = int.from_bytes(b[:32], "little")
    x1 = int.from_bytes(bytes(b[32:63]) + bytes([b[63] & 0x3F]), "little")
    if flags & 0x40:
        return None
    if x0 >= Q or x1 >= Q:
        raise ValueError("G2 x coordinate out of range")
    x = (x0, x1)
    y2 = fq2_add(fq2_mul(fq2_sq(x), x), G2_B)
    y = _sqrt_fq2(y2)
    if y is None:
        raise ValueError("not a point on G2")
    if bool(flags & 0x80) != _fq2_is_neg(y):
        y = ((Q - y[0]) % Q, (Q - y[1]) % Q)
    pt = (x, y)
    # BN254 G2 has a large cofactor: enforce the prime-order subgroup, as
    # the ark-serialize validated deserializer does
    from ..ops.refmath import G2 as _G2
    from ..ops.constants import R as _R

    if _G2.scalar_mul(pt, _R) is not None:
        raise ValueError("G2 point not in the prime-order subgroup")
    return pt


def proof_to_bytes(proof: Proof) -> bytes:
    return (
        g1_to_bytes(proof.a) + g2_to_bytes(proof.b) + g1_to_bytes(proof.c)
    )


def proof_from_bytes(b: bytes) -> Proof:
    assert len(b) == 128, f"proof must be 128 bytes, got {len(b)}"
    return Proof(
        a=g1_from_bytes(b[:32]),
        b=g2_from_bytes(b[32:96]),
        c=g1_from_bytes(b[96:128]),
    )
