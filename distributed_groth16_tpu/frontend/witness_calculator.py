"""Circom WASM witness calculator on the pure-Python interpreter.

Mirrors the reference's wasmer-based calculator
(ark-circom/src/witness/witness_calculator.rs:17-299) including both ABIs:

* **Circom 2** (`getVersion() == 2`): field elements move through the shared
  RW memory as big-endian sequences of u32 (witness_calculator.rs:219-255);
  inputs via `setInputSignal(fnv_msb, fnv_lsb, index)`.
* **Circom 1**: field elements live in linear memory in the snarkjs tagged
  layout (short / long / long-Montgomery, memory.rs:108-196); inputs via
  `getSignalOffset32` + `setSignal`, outputs via `getPWitness` + a tagged
  read.

Signal names are addressed by their 64-bit FNV-1a hash split into two u32s
(witness/mod.rs:18-24).
"""

from __future__ import annotations

from .wasm_vm import HostExit, Instance, Module

__all__ = ["WitnessCalculator", "fnv1a_64"]

# BN254 Fr — the only prime circom's snarkjs toolchain emits for these
# fixtures; the generic path reads the prime from the module itself.
_R_INV = 9915499612839321149637521777990102151350674507940716049588462388200839649614


def fnv1a_64(s: str) -> tuple[int, int]:
    """64-bit FNV-1a of a signal name -> (msb32, lsb32)."""
    h = 0xCBF29CE484222325
    for b in s.encode():
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return (h >> 32) & 0xFFFFFFFF, h & 0xFFFFFFFF


def _host_funcs(collector):
    def error(*a):
        raise HostExit(a)

    def exception_handler(code):
        if code:
            raise HostExit(code)

    def noop(*a):
        return 0

    return {
        ("runtime", "error"): error,
        ("runtime", "exceptionHandler"): exception_handler,
        ("runtime", "logSetSignal"): noop,
        ("runtime", "logGetSignal"): noop,
        ("runtime", "logFinishComponent"): noop,
        ("runtime", "logStartComponent"): noop,
        ("runtime", "log"): noop,
        ("runtime", "showSharedRWMemory"): noop,
        ("runtime", "printErrorMessage"): noop,
        ("runtime", "writeBufferMessage"): noop,
    }


class WitnessCalculator:
    def __init__(self, wasm_bytes: bytes, engine: str = "auto"):
        """engine: "auto" (C tier when buildable — ~100x the throughput of
        the Python VM — else Python), "c", or "python". DG16_NO_CWASM=1
        forces the Python VM globally."""
        from ..utils import config as _config

        self.module = Module(wasm_bytes)
        use_c = engine == "c" or (
            engine == "auto" and not _config.env_flag("DG16_NO_CWASM")
        )
        self.inst = None
        self._auto = engine == "auto"
        if use_c:
            try:
                from .wasm_cexec import CInstance

                self.inst = CInstance(self.module, _host_funcs(self))
            except ImportError:
                if engine == "c":
                    raise
        if self.inst is None:
            self.inst = Instance(self.module, _host_funcs(self))
            self._auto = False
        try:
            self.version = self.inst.call("getVersion")[0]
        except KeyError:
            self.version = 1
        if self.version >= 2:
            self.n32 = self.inst.call("getFieldNumLen32")[0]
            self.inst.call("getRawPrime")
            words = [
                self.inst.call("readSharedRWMemory", [i])[0]
                for i in range(self.n32)
            ]
            self.prime = 0
            for w in reversed(words):  # words are little-endian u32s
                self.prime = (self.prime << 32) | w
        else:
            self.n32 = (self.inst.call("getFrLen")[0] >> 2) - 2
            ptr = self.inst.call("getPRawPrime")[0]
            self.prime = int.from_bytes(
                self.inst.memory[ptr : ptr + self.n32 * 4], "little"
            )

    @classmethod
    def from_file(cls, path) -> "WitnessCalculator":
        with open(path, "rb") as f:
            return cls(f.read())

    # -- Circom 1 tagged memory (memory.rs:108-196) --------------------------

    def _read_fr(self, ptr: int) -> int:
        mem = self.inst.memory
        if mem[ptr + 7] & 0x80:
            num = int.from_bytes(mem[ptr + 8 : ptr + 8 + self.n32 * 4], "little")
            if mem[ptr + 7] & 0x40:
                num = num * _R_INV % self.prime
            return num
        num = int.from_bytes(mem[ptr : ptr + 4], "little")
        if mem[ptr + 3] & 0x40:
            num -= 0x100000000  # small negative
        return num

    def _write_fr(self, ptr: int, value: int):
        mem = self.inst.memory
        short_max = 0x80000000
        short_min = self.prime - short_max  # as signed: -(2^31)
        v = value % self.prime
        signed = v if v < short_max else v - self.prime
        if -short_max < signed < short_max and abs(signed) < short_min:
            # short form: i32 value, tag word 0
            mem[ptr : ptr + 4] = (signed & 0xFFFFFFFF).to_bytes(4, "little")
            mem[ptr + 4 : ptr + 8] = b"\x00\x00\x00\x00"
        else:
            mem[ptr : ptr + 4] = b"\x00\x00\x00\x00"
            mem[ptr + 4 : ptr + 8] = b"\x00\x00\x00\x80"  # long tag
            mem[ptr + 8 : ptr + 8 + self.n32 * 4] = v.to_bytes(
                self.n32 * 4, "little"
            )

    def _read_u32(self, ptr):
        return int.from_bytes(self.inst.memory[ptr : ptr + 4], "little")

    def _write_u32(self, ptr, v):
        self.inst.memory[ptr : ptr + 4] = v.to_bytes(4, "little")

    # -- witness computation --------------------------------------------------

    def calculate_witness(self, inputs: dict, sanity_check: bool = False):
        """inputs: {signal name: int | list[int]} -> list of witness ints."""
        try:
            self.inst.call("init", [1 if sanity_check else 0])
            if self.version >= 2:
                return self._calculate_circom2(inputs)
            return self._calculate_circom1(inputs)
        except Exception as e:
            from .wasm_cexec import WasmMemoryLimit

            # the C tier caps linear memory; the Python VM grows without
            # bound — retry there so huge circuits keep working under the
            # default engine. Any other trap re-raises as-is.
            if not (self._auto and isinstance(e, WasmMemoryLimit)):
                raise
            self.inst = Instance(self.module, _host_funcs(self))
            self._auto = False
            self.inst.call("init", [1 if sanity_check else 0])
            if self.version >= 2:
                return self._calculate_circom2(inputs)
            return self._calculate_circom1(inputs)

    def _values(self, v):
        if isinstance(v, (list, tuple)):
            out = []
            for x in v:
                out.extend(self._values(x))
            return out
        return [int(v)]

    def _calculate_circom2(self, inputs):
        n32 = self.n32
        for name, v in inputs.items():
            msb, lsb = fnv1a_64(name)
            for i, value in enumerate(self._values(v)):
                val = value % self.prime
                for j in range(n32):
                    self.inst.call(
                        "writeSharedRWMemory",
                        [j, (val >> (32 * j)) & 0xFFFFFFFF],
                    )
                self.inst.call("setInputSignal", [msb, lsb, i])
        size = self.inst.call("getWitnessSize")[0]
        out = []
        for i in range(size):
            self.inst.call("getWitness", [i])
            acc = 0
            for j in range(n32):
                acc |= self.inst.call("readSharedRWMemory", [j])[0] << (32 * j)
            out.append(acc)
        return out

    def _calculate_circom1(self, inputs):
        old_free = self._read_u32(0)
        p_sig = self._alloc(8)
        p_fr = self._alloc(self.n32 * 4 + 8)
        for name, v in inputs.items():
            msb, lsb = fnv1a_64(name)
            self.inst.call("getSignalOffset32", [p_sig, 0, msb, lsb])
            sig_offset = self._read_u32(p_sig)
            for i, value in enumerate(self._values(v)):
                self._write_fr(p_fr, value)
                self.inst.call("setSignal", [0, 0, sig_offset + i, p_fr])
        n_vars = self.inst.call("getNVars")[0]
        out = []
        for i in range(n_vars):
            ptr = self.inst.call("getPWitness", [i])[0]
            out.append(self._read_fr(ptr) % self.prime)
        self._write_u32(0, old_free)
        return out

    def _alloc(self, size):
        p = self._read_u32(0)
        self._write_u32(0, p + size)
        return p
