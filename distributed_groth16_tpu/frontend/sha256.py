"""SHA-256 as a native R1CS circuit — the framework's headline workload.

The reference's flagship benchmark proves a circom-compiled SHA-256 circuit
(fixtures/sha256, m = 32768, groth16/examples/sha256.rs). The circom
fixture's compiled wasm can't run here (no WASM runtime), so the same
workload is built natively with frontend.r1cs.ConstraintSystem: one
512-bit block, standard FIPS-180 compression in bit-level constraints.

Constraint shapes (one per bit unless noted):
  boolean b      : b*b = b
  xor z = x^y    : 2x*y = x + y - z
  ch  z = ef^(~e)g : e*(f - g) = z - g
  maj via m = bc : a*(b + c - 2m) = z - m          (2 constraints/bit)
  rot/shift      : free (wire re-indexing)
  add mod 2^32   : one linear constraint over bit-weighted sums plus
                   booleanity of the 32 output + carry bits
The per-round temp1/temp2 sums are folded directly into the e' and a'
additions (6/7-term adds) to keep the circuit inside the reference's
m = 32768 domain.

Differentially tested against hashlib.sha256 (tests/test_sha256.py).
"""

from __future__ import annotations

import hashlib
import struct

from ..ops.constants import R
from .r1cs import ConstraintSystem

_K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
    0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
    0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
    0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
    0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]
_H0 = [
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
]


class _Builder:
    """Word = 32 wire indices, LSB first; index -1 = constant 0."""

    def __init__(self, cs: ConstraintSystem):
        self.cs = cs

    # -- wires ---------------------------------------------------------------

    def val(self, w: int) -> int:
        return 0 if w == -1 else self.cs.value(w)

    def bool_new(self, v: int) -> int:
        w = self.cs.new_witness(v & 1)
        self.cs.enforce([(1, w)], [(1, w)], [(1, w)])
        return w

    def const_word(self, k: int) -> list:
        """Constant word for linear contexts (add_words): no wires."""
        return [("const", (k >> i) & 1) for i in range(32)]

    def pinned_word(self, k: int) -> list[int]:
        """Constant word as wires pinned by one constraint per bit — for
        non-linear contexts (xor/ch/maj on the initial state)."""
        out = []
        for i in range(32):
            bit = (k >> i) & 1
            w = self.cs.new_witness(bit)
            self.cs.enforce(
                [(bit, self.cs.ONE)], [(1, self.cs.ONE)], [(1, w)]
            )
            out.append(w)
        return out

    # -- bit ops -------------------------------------------------------------

    def xor(self, x: int, y: int) -> int:
        vz = self.val(x) ^ self.val(y)
        z = self.cs.new_witness(vz)
        # 2xy = x + y - z
        self.cs.enforce(
            [(2, x)] if x != -1 else [],
            [(1, y)] if y != -1 else [],
            _lc_sub([x, y], z),
        )
        return z

    def xor3(self, x: int, y: int, z: int) -> int:
        return self.xor(self.xor(x, y), z)

    def ch(self, e: int, f: int, g: int) -> int:
        vz = (self.val(e) & self.val(f)) ^ ((1 - self.val(e)) & self.val(g))
        z = self.cs.new_witness(vz)
        # e*(f - g) = z - g
        self.cs.enforce(
            [(1, e)],
            _lc_diff(f, g),
            _lc_diff(z, g),
        )
        return z

    def maj(self, a: int, b: int, c: int) -> int:
        va, vb, vc = self.val(a), self.val(b), self.val(c)
        vm = vb & vc
        m = self.cs.new_witness(vm)
        self.cs.enforce(
            [(1, b)] if b != -1 else [],
            [(1, c)] if c != -1 else [],
            [(1, m)],
        )
        vz = (va & vb) ^ (va & vc) ^ vm
        z = self.cs.new_witness(vz)
        # a*(b + c - 2m) = z - m
        bc = []
        if b != -1:
            bc.append((1, b))
        if c != -1:
            bc.append((1, c))
        bc.append((R - 2, m))
        self.cs.enforce([(1, a)], bc, _lc_diff(z, m))
        return z

    # -- word ops ------------------------------------------------------------

    @staticmethod
    def rotr(word: list, n: int) -> list:
        return [word[(i + n) % 32] for i in range(32)]

    @staticmethod
    def shr(word: list, n: int) -> list:
        return [word[i + n] if i + n < 32 else -1 for i in range(32)]

    def word_val(self, word: list) -> int:
        acc = 0
        for i, w in enumerate(word):
            bit = w[1] if isinstance(w, tuple) else self.val(w)
            acc |= bit << i
        return acc

    def xor3_word(self, x: list, y: list, z: list) -> list:
        return [self.xor3(x[i], y[i], z[i]) for i in range(32)]

    def add_words(self, words: list[list], n_carry: int) -> list:
        """Sum words mod 2^32: allocate 32 result bits + n_carry carry bits
        and one linear constraint sum(words) == result + 2^32 * carry."""
        total = sum(self.word_val(w) for w in words)
        out_v = total & 0xFFFFFFFF
        carry_v = total >> 32
        assert carry_v < (1 << n_carry), "carry budget too small"
        out = [self.bool_new((out_v >> i) & 1) for i in range(32)]
        carry = [self.bool_new((carry_v >> i) & 1) for i in range(n_carry)]
        lc = []
        const_acc = 0
        for w in words:
            for i, bit in enumerate(w):
                if isinstance(bit, tuple):
                    const_acc += bit[1] << i
                elif bit != -1:
                    lc.append(((1 << i) % R, bit))
        if const_acc:
            lc.append((const_acc % R, self.cs.ONE))
        rhs = [((1 << i) % R, out[i]) for i in range(32)] + [
            ((1 << (32 + i)) % R, carry[i]) for i in range(n_carry)
        ]
        self.cs.enforce(lc, [(1, self.cs.ONE)], rhs)
        return out


def _lc_diff(a: int, b: int) -> list:
    lc = []
    if a != -1:
        lc.append((1, a))
    if b != -1:
        lc.append((R - 1, b))
    return lc


def _lc_sub(xs: list[int], z: int) -> list:
    lc = [(1, x) for x in xs if x != -1]
    lc.append((R - 1, z))
    return lc


def sha256_padded_block(message: bytes) -> bytes:
    """FIPS-180 padding for a single-block (<= 55 byte) message."""
    assert len(message) <= 55, "single-block circuit: message <= 55 bytes"
    bitlen = len(message) * 8
    block = message + b"\x80" + b"\x00" * (55 - len(message))
    return block + struct.pack(">Q", bitlen)


def sha256_circuit(message: bytes) -> tuple[ConstraintSystem, list[int]]:
    """Build the one-block SHA-256 circuit for `message`.

    Public inputs (2): the digest packed as two 128-bit field elements
    (big-endian halves). Private witness: the 512 padded message bits and
    all internal wires. Returns (cs, expected_public_inputs).
    """
    block = sha256_padded_block(message)
    digest = hashlib.sha256(message).digest()
    hi = int.from_bytes(digest[:16], "big")
    lo = int.from_bytes(digest[16:], "big")

    cs = ConstraintSystem()
    out_hi = cs.new_instance(hi)
    out_lo = cs.new_instance(lo)
    b = _Builder(cs)

    # message bits as boolean witnesses, words big-endian per FIPS-180
    words = []
    for w in range(16):
        word_int = struct.unpack(">I", block[4 * w : 4 * w + 4])[0]
        words.append([b.bool_new((word_int >> i) & 1) for i in range(32)])

    # message schedule
    for t in range(16, 64):
        s0 = b.xor3_word(
            b.rotr(words[t - 15], 7),
            b.rotr(words[t - 15], 18),
            b.shr(words[t - 15], 3),
        )
        s1 = b.xor3_word(
            b.rotr(words[t - 2], 17),
            b.rotr(words[t - 2], 19),
            b.shr(words[t - 2], 10),
        )
        words.append(
            b.add_words([words[t - 16], s0, words[t - 7], s1], n_carry=2)
        )

    # compression; fold temp1/temp2 into the e'/a' additions to stay
    # inside m = 32768
    state = [b.pinned_word(h) for h in _H0]
    for t in range(64):
        a, bb, c, d, e, f, g, h = state
        big_s1 = b.xor3_word(b.rotr(e, 6), b.rotr(e, 11), b.rotr(e, 25))
        ch = [b.ch(e[i], f[i], g[i]) for i in range(32)]
        big_s0 = b.xor3_word(b.rotr(a, 2), b.rotr(a, 13), b.rotr(a, 22))
        mj = [b.maj(a[i], bb[i], c[i]) for i in range(32)]
        kw = b.const_word(_K[t])
        # e' = d + h + S1 + ch + K + W   (6 terms)
        e_new = b.add_words([d, h, big_s1, ch, kw, words[t]], n_carry=3)
        # a' = h + S1 + ch + K + W + S0 + maj   (7 terms)
        a_new = b.add_words(
            [h, big_s1, ch, kw, words[t], big_s0, mj], n_carry=3
        )
        state = [a_new, a, bb, c, e_new, e, f, g]

    # digest = H0 + state, re-packed into two public field elements
    digest_words = [
        b.add_words([b.const_word(_H0[i]), state[i]], n_carry=1)
        for i in range(8)
    ]
    # hi = words 0..3 big-endian, lo = words 4..7
    def pack_lc(word_slice):
        lc = []
        for wi, word in enumerate(word_slice):
            word_shift = 32 * (3 - wi)
            for i in range(32):
                lc.append(((1 << (word_shift + i)) % R, word[i]))
        return lc

    cs.enforce(pack_lc(digest_words[:4]), [(1, cs.ONE)], [(1, out_hi)])
    cs.enforce(pack_lc(digest_words[4:]), [(1, cs.ONE)], [(1, out_lo)])
    return cs, [hi, lo]
