"""distributed_groth16_tpu — a TPU-native collaborative Groth16 proving
framework (JAX/XLA/Pallas), providing the capabilities of the reference
zkSaaS prover (zkHubHQ/distributed-groth16): packed secret sharing, star
collectives, distributed NTT/MSM kernels, and the Groth16 prover/service
stack — re-designed for TPU meshes.

Layer map (mirrors SURVEY.md §1):
    ops/       field arithmetic, NTT, curve ops, MSM   (device kernels)
    parallel/  net collectives, PSS, d_fft/d_msm/d_pp  (the "mpc-net"+"dist-primitives" role)
    models/    groth16 prover/setup/verifier           (the "groth16" crate role)
    frontend/  circom r1cs/zkey/wtns readers, witness  (the "ark-circom" role)
    service/   proof-job queue, worker pool, CRS cache (docs/SERVICE.md)
    api/, cli  HTTP proving service + client           (the "mpc-api"/"zk-cli" role)
"""

import os

import jax

# Persistent compilation cache: our kernels are built from deep uint32 limb
# graphs; caching compiled executables across processes matters for tests,
# benches and the service alike. Partitioned by CPU fingerprint
# (utils/cache.py): XLA:CPU AOT entries from a host with different vector
# features can SIGILL on load, and driver rounds hop between hosts.
try:
    from .utils import config as _config

    if _config.env_flag("DG16_NO_JAX_CACHE"):
        from .utils.cache import disable_compile_cache

        disable_compile_cache(jax)
    elif cache_dir := _config.env_str("DG16_JAX_CACHE"):
        jax.config.update(
            "jax_compilation_cache_dir", os.path.abspath(cache_dir)
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    else:
        from .utils.cache import setup_compile_cache

        setup_compile_cache(
            jax, os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
        )
except Exception:  # pragma: no cover - older jax without these flags
    pass

__version__ = "0.1.0"
