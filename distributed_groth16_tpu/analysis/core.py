"""Rule framework: findings, module/project model, registry, suppressions.

Deliberately stdlib-only (ast + re + pathlib): the lint must run on a
bare interpreter — CI's lint lane and ``tools/dg16lint`` load it without
jax installed — so nothing in ``analysis/`` may import the rest of the
package. Rules that need project context (docs files, utils/config.py)
read those files as text/AST, never import them.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Iterator

# -- findings ----------------------------------------------------------------


@dataclass(frozen=True, order=True)
class Finding:
    """One lint hit. `line` is 1-based (0 = whole file), `col` 0-based."""

    path: str  # project-root-relative posix path
    line: int
    col: int
    rule: str  # "DG101"
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


# -- suppressions ------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*dg16lint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+)"
)


def _parse_suppressions(lines: list[str]) -> tuple[dict[int, set], set]:
    """Per-line {lineno: {rule ids}} and the whole-file suppression set.
    The id ``all`` wildcards every rule."""
    per_line: dict[int, set] = {}
    per_file: set = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = {t.strip().upper() for t in m.group(2).split(",") if t.strip()}
        if m.group(1) == "disable-file":
            per_file |= ids
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, per_file


# -- module / project model --------------------------------------------------


class Module:
    """One parsed source file: path, text, AST, lazy parent map."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.parse_error: SyntaxError | None = None
        try:
            self.tree: ast.Module | None = ast.parse(source)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        self._parents: dict[ast.AST, ast.AST] | None = None
        self.suppress_line, self.suppress_file = _parse_suppressions(self.lines)

    def parent(self, node: ast.AST) -> ast.AST | None:
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for p in ast.walk(self.tree):
                    for c in ast.iter_child_nodes(p):
                        self._parents[c] = p
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        for ids in (
            self.suppress_file,
            self.suppress_line.get(lineno, ()),
        ):
            if rule_id in ids or "ALL" in ids:
                return True
        return False


class Project:
    """The scanned tree: a root dir (holding docs/, the package, ...) and
    the parsed modules under the target paths."""

    def __init__(self, root: Path, modules: list[Module]):
        self.root = root
        self.modules = modules

    def module(self, relpath_suffix: str) -> Module | None:
        for m in self.modules:
            if m.relpath.endswith(relpath_suffix):
                return m
        return None

    def doc_text(self, relpath: str) -> str | None:
        p = self.root / relpath
        try:
            return p.read_text()
        except OSError:
            return None


# -- rule registry -----------------------------------------------------------


@dataclass
class Rule:
    id: str
    name: str
    doc: str
    # per-module hook: (module, project) -> findings
    check_module: Callable | None = None
    # once-per-run hook: (project) -> findings
    check_project: Callable | None = None


_RULES: dict[str, Rule] = {}


def rule(id: str, name: str, doc: str, *, project_wide: bool = False):
    """Register the decorated checker under `id`. The checker is the
    per-module hook unless `project_wide`, then it runs once per project."""

    def wrap(fn):
        r = _RULES.get(id) or Rule(id, name, doc)
        if project_wide:
            r.check_project = fn
        else:
            r.check_module = fn
        _RULES[id] = r
        return fn

    return wrap


def all_rules() -> dict[str, Rule]:
    from . import rules  # noqa: F401 — importing registers every DG1xx

    return dict(_RULES)


# -- file walking + runner ---------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "node_modules"}


def iter_py_files(target: Path) -> Iterator[Path]:
    if target.is_file():
        if target.suffix == ".py":
            yield target
        return
    for p in sorted(target.rglob("*.py")):
        # judge only components below the scan target: an ancestor like
        # ~/.jenkins must not silently blank the whole run
        parts = p.relative_to(target).parts
        if not any(part in _SKIP_DIRS or part.startswith(".") for part in parts):
            yield p


def find_root(target: Path) -> Path:
    """Project root: nearest ancestor (incl. target) carrying repo
    markers; else the target's parent directory."""
    t = target if target.is_dir() else target.parent
    for d in (t, *t.parents):
        if (d / "pytest.ini").exists() or (d / ".git").exists() or (
            d / "docs"
        ).is_dir():
            return d
    return t


def load_project(paths: Iterable[Path], root: Path | None = None) -> Project:
    paths = [Path(p) for p in paths]
    root = Path(root) if root is not None else find_root(paths[0])
    modules: list[Module] = []
    seen: set = set()
    for target in paths:
        for f in iter_py_files(target):
            f = f.resolve()
            if f in seen:
                continue
            seen.add(f)
            try:
                rel = f.relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            try:
                modules.append(Module(f, rel, f.read_text()))
            except (OSError, UnicodeDecodeError) as e:
                m = Module(f, rel, "")
                m.parse_error = SyntaxError(f"unreadable: {e}")
                modules.append(m)
    return Project(root, modules)


def run_rules(
    project: Project, select: set | None = None
) -> tuple[list[Finding], int]:
    """All unsuppressed findings (sorted) + the count suppressed inline."""
    rules = all_rules()
    if select:
        rules = {k: v for k, v in rules.items() if k in select}
    raw: list[Finding] = []
    for mod in project.modules:
        if mod.parse_error is not None:
            raw.append(
                Finding(
                    mod.relpath,
                    getattr(mod.parse_error, "lineno", 0) or 0,
                    0,
                    "DG000",
                    f"could not parse file: {mod.parse_error.msg}",
                )
            )
            continue
        for r in rules.values():
            if r.check_module is not None:
                raw.extend(r.check_module(mod, project))
    for r in rules.values():
        if r.check_project is not None:
            raw.extend(r.check_project(project))

    by_rel = {m.relpath: m for m in project.modules}
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            suppressed += 1
            continue
        findings.append(f)
    return sorted(set(findings)), suppressed


# -- shared AST helpers (used by several rules) ------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains; None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_kw(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def walk_no_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk, but do not descend into nested function/lambda bodies
    (their execution context is the caller's, not this scope's)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
