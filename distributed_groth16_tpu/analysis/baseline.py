"""Checked-in baseline: grandfathered findings that don't fail the run.

A baseline entry is a *fingerprint*, not a line number — the hash covers
(rule, file, the finding line's stripped text, duplicate index) so code
moving up or down a file doesn't churn the baseline, while editing the
offending line invalidates its entry (the finding resurfaces as new,
which is the point: touched code must come clean).
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from .core import Finding, Project

BASELINE_VERSION = 1
DEFAULT_BASELINE = "tools/dg16lint-baseline.json"


def fingerprint(f: Finding, project: Project) -> str:
    mod = next((m for m in project.modules if m.relpath == f.path), None)
    # non-module paths (DG104 rows in docs/OBSERVABILITY.md) have no line
    # text to anchor on — hash the message so distinct doc findings don't
    # collapse into one grandfathering entry
    anchor = mod.line_text(f.line).strip() if mod is not None else f.message
    body = f"{f.rule}|{f.path}|{anchor}"
    h = hashlib.sha1(body.encode()).hexdigest()[:16]
    return h


def fingerprints(findings: list[Finding], project: Project) -> dict[str, str]:
    """finding -> fingerprint, de-duplicating identical lines with a
    positional suffix so two equal hits on one line get distinct ids."""
    seen: dict[str, int] = {}
    out: dict[Finding, str] = {}
    for f in findings:  # findings arrive sorted — stable indices
        fp = fingerprint(f, project)
        n = seen.get(fp, 0)
        seen[fp] = n + 1
        out[f] = fp if n == 0 else f"{fp}#{n}"
    return out


class BaselineError(Exception):
    """The baseline file exists but can't be used (bad JSON / shape)."""


def load(path: Path) -> dict[str, dict]:
    """{fingerprint: entry} from a baseline file; {} when absent.

    Raises BaselineError (not a raw traceback) on a corrupt or
    hand-mangled file — trailing comma, entry missing "fingerprint" —
    so the CLI can say which file to fix or regenerate."""
    try:
        data = json.loads(Path(path).read_text())
    except FileNotFoundError:
        return {}
    except OSError as e:
        # an unreadable file must not silently report everything as new
        raise BaselineError(
            f"unreadable baseline file {path}: {e}"
        ) from e
    except ValueError as e:
        raise BaselineError(
            f"invalid baseline file {path}: {e} — fix it or regenerate "
            "with --write-baseline"
        ) from e
    try:
        return {e["fingerprint"]: e for e in data.get("findings", [])}
    except (ValueError, TypeError, KeyError, AttributeError) as e:
        raise BaselineError(
            f"invalid baseline file {path}: {e!r} — fix it or regenerate "
            "with --write-baseline"
        ) from e


def save(
    path: Path,
    findings: list[Finding],
    project: Project,
    keep: list[dict] | None = None,
) -> None:
    """Write the baseline; `keep` carries pre-existing entries to retain
    verbatim (the un-selected rules' grandfathered findings when the run
    was narrowed with --select)."""
    fps = fingerprints(findings, project)
    entries = [
        {
            "fingerprint": fps[f],
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
        }
        for f in findings
    ] + list(keep or [])
    doc = {
        "version": BASELINE_VERSION,
        "comment": (
            "dg16lint grandfathered findings; regenerate with "
            "`python -m distributed_groth16_tpu.analysis --write-baseline`"
        ),
        "findings": entries,
    }
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")


def split(
    findings: list[Finding], project: Project, baseline: dict[str, dict]
) -> tuple[list[Finding], list[Finding], list[str]]:
    """(new, grandfathered, stale-fingerprints) against the baseline."""
    fps = fingerprints(findings, project)
    new: list[Finding] = []
    old: list[Finding] = []
    used: set = set()
    for f in findings:
        fp = fps[f]
        if fp in baseline:
            old.append(f)
            used.add(fp)
        else:
            new.append(f)
    stale = [fp for fp in baseline if fp not in used]
    return new, old, stale
