"""dg16lint — project-native static analysis for distributed_groth16_tpu.

The zkSaaS design's core guarantee is that no single server learns the
witness; the repo's failure modes (blocked event loops, mismatched
king/client collectives, secret values reaching a log line or a metric
label, jitted code silently falling back to Python control flow) are
exactly the bugs tests miss and an AST pass catches. This package is a
small rule framework plus seven project-specific rules:

    DG101  async-blocking        blocking calls inside ``async def``
    DG102  secret-taint          witness/trapdoor identifiers at log/span/
                                 metric/DTO/dump sinks; unstripped
                                 ProvingKey reaching serialization
    DG103  env-knob discipline   DG16_* env reads outside utils/config.py;
                                 knobs declared but undocumented
    DG104  metric-catalog drift  code registrations vs the
                                 docs/OBSERVABILITY.md catalog
    DG105  lock-discipline       ``# guarded-by: _lock`` attributes mutated
                                 outside ``with self._lock``
    DG106  tracer-hygiene        Python control flow on traced values in
                                 jit/mesh_jit/shard_map functions
    DG107  collective-pairing    king/client MpcNet collective sequences
                                 must pair up (static deadlock detector)

Run it with ``python -m distributed_groth16_tpu.analysis`` or
``tools/dg16lint`` (the latter needs no third-party deps — the whole
package is stdlib-only and self-contained; nothing here may import jax or
any sibling package). Findings are suppressed inline with
``# dg16lint: disable=DG1xx`` (same line) or
``# dg16lint: disable-file=DG1xx`` (whole file), or grandfathered in the
checked-in baseline (``tools/dg16lint-baseline.json``). See
docs/STATIC_ANALYSIS.md for the rule catalog.
"""

from .core import Finding, Module, Project, Rule, all_rules, rule  # noqa: F401
from .cli import main  # noqa: F401
