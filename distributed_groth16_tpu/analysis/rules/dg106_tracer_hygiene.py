"""DG106 — tracer hygiene in jitted functions.

``if``/``while``/``bool()``/``assert`` on a *value* derived from a
parameter of a ``jax.jit`` / ``mesh_jit`` / ``shard_map`` function
forces a trace-time concretization: under jit it either raises a
ConcretizationTypeError or — worse, with weak typing through ``int()``
or numpy coercion — silently bakes one branch into the compiled program
and recompiles per value, the "jitted code falling back to Python
control flow" failure mode the kernel roadmap work must not reintroduce.

Shape/dtype-derived branching (``x.shape[0] == 4``, ``x.ndim``,
``len(x)``) is static under tracing and exempt, as are parameters named
by ``static_argnums`` / ``static_argnames``. Jitted functions are found
by decorator (including ``functools.partial(jax.jit, ...)``) and by
same-module wrapper calls (``jax.jit(f)``, ``mesh_jit("name", f)``,
``shard_map(f, ...)``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, Module, Project, call_kw, dotted_name, rule

_JIT_NAMES = {"jit", "pjit", "mesh_jit", "timed_jit", "shard_map"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance"}


def _is_jit_ref(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.split(".")[-1] in _JIT_NAMES


def _static_params(call: ast.Call, fn: ast.arguments) -> set[str]:
    """Parameter names excluded from tracing via static_argnums/names."""
    out: set[str] = set()
    posnames = [a.arg for a in fn.posonlyargs + fn.args]
    nums = call_kw(call, "static_argnums")
    items = []
    if isinstance(nums, ast.Constant):
        items = [nums.value]
    elif isinstance(nums, (ast.Tuple, ast.List)):
        items = [e.value for e in nums.elts if isinstance(e, ast.Constant)]
    for i in items:
        if isinstance(i, int) and 0 <= i < len(posnames):
            out.add(posnames[i])
    names = call_kw(call, "static_argnames")
    elts = []
    if isinstance(names, ast.Constant):
        elts = [names]
    elif isinstance(names, (ast.Tuple, ast.List)):
        elts = list(names.elts)
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _jitted_functions(
    module: Module,
) -> Iterator[tuple[ast.FunctionDef, set[str]]]:
    """(function, static-param-names) for every jit-compiled function:
    decorated directly, via functools.partial(jax.jit, ...), or passed to
    a jit wrapper call elsewhere in the module."""
    assert module.tree is not None
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)

    seen: set[ast.FunctionDef] = set()

    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                static: set[str] = set()
                target = dec
                if isinstance(dec, ast.Call):
                    fn_name = dotted_name(dec.func)
                    if fn_name is not None and fn_name.split(".")[-1] == "partial":
                        if dec.args and _is_jit_ref(dec.args[0]):
                            static = _static_params(dec, node.args)
                            target = dec.args[0]
                        else:
                            continue
                    else:
                        static = _static_params(dec, node.args)
                        target = dec.func
                if _is_jit_ref(target) and node not in seen:
                    seen.add(node)
                    yield node, static
        elif isinstance(node, ast.Call) and _is_jit_ref(node.func):
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    fn = defs[arg.id]
                    if fn not in seen:
                        seen.add(fn)
                        yield fn, _static_params(node, fn.args)


def _value_refs(expr: ast.AST, tainted: set[str], module: Module) -> set[str]:
    """Tainted names referenced *by value* in expr — occurrences whose
    every use is via .shape/.ndim/.dtype/.size or len() are static and
    don't count."""
    hits: set[str] = set()
    parents: dict[ast.AST, ast.AST] = {}
    for p in ast.walk(expr):
        for c in ast.iter_child_nodes(p):
            parents[c] = p
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Name) and node.id in tainted):
            continue
        parent = parents.get(node)
        if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
            continue
        if isinstance(parent, ast.Call) and parent.func is not node:
            fname = dotted_name(parent.func)
            if fname in _STATIC_CALLS:
                continue
        hits.add(node.id)
    return hits


def _check_fn(
    fn: ast.FunctionDef, static: set[str], module: Module
) -> Iterator[Finding]:
    args = fn.args
    tainted = {
        a.arg
        for a in args.posonlyargs + args.args + args.kwonlyargs
        if a.arg not in static and a.arg != "self"
    }
    if args.vararg:
        tainted.add(args.vararg.arg)

    def visit(body: list[ast.stmt]):
        for stmt in body:
            # propagate taint through simple assignments, in order
            if isinstance(stmt, ast.Assign) and _value_refs(
                stmt.value, tainted, module
            ):
                for t in stmt.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            tainted.add(sub.id)
            test = None
            what = None
            if isinstance(stmt, (ast.If, ast.While)):
                test, what = stmt.test, type(stmt).__name__.lower()
            elif isinstance(stmt, ast.Assert):
                test, what = stmt.test, "assert"
            if test is not None:
                for name in sorted(_value_refs(test, tainted, module)):
                    yield Finding(
                        module.relpath, stmt.lineno, stmt.col_offset,
                        "DG106",
                        f"Python `{what}` on traced value `{name}` inside "
                        f"jitted `{fn.name}` — use jnp.where/lax.cond or "
                        "make it a static argument",
                    )
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.Call):
                    fname = dotted_name(sub.func)
                    if fname == "bool":
                        for name in sorted(
                            _value_refs(sub, tainted, module)
                        ):
                            yield Finding(
                                module.relpath, sub.lineno, sub.col_offset,
                                "DG106",
                                f"bool() on traced value `{name}` inside "
                                f"jitted `{fn.name}` — concretizes at "
                                "trace time",
                            )
            # recurse into nested blocks (same taint scope)
            for field_name in ("body", "orelse", "finalbody"):
                sub_body = getattr(stmt, field_name, None)
                if sub_body and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield from visit(sub_body)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from visit(handler.body)

    yield from visit(fn.body)


@rule(
    "DG106",
    "tracer-hygiene",
    "Python if/while/bool/assert on a value derived from a jitted "
    "function's traced parameters — concretization error or silent "
    "per-value recompilation; shape/dtype/static-arg branching is exempt.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    for fn, static in _jitted_functions(module):
        yield from _check_fn(fn, static, module)
