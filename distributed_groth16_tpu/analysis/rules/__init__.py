"""Rule modules — importing this package registers every DG1xx rule."""

from . import (  # noqa: F401
    dg101_async_blocking,
    dg102_secret_taint,
    dg103_env_knobs,
    dg104_metric_catalog,
    dg105_lock_discipline,
    dg106_tracer_hygiene,
    dg107_collective_pairing,
    dg108_print_discipline,
)
