"""DG108 — ``print()`` in package code.

The logging spine (telemetry/logbus.py) only sees records that go
through the stdlib ``logging`` tree: a ``print()`` bypasses the ring,
the level filter, the storm suppressor, the secret redactor, and every
query surface (`GET /logs`, the job DTO tail, flight dumps) at once. In
a service whose debugging story is "give me the job's correlated log
stream", an un-ringed print is telemetry that silently never happened.

Allowed:
  * CLI surfaces — modules named ``cli.py`` / ``__main__.py``, where
    stdout IS the product;
  * code lexically inside a function named ``main`` (the argparse entry
    points of benchgate.py, certs.py, ...);
  * deliberate stdout emitters carrying ``# dg16lint: disable=DG108``.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from ..core import Finding, Module, Project, rule

_CLI_BASENAMES = {"cli.py", "__main__.py"}
_CLI_FUNCS = {"main"}


def _prints(node: ast.AST, allowed: bool) -> Iterator[ast.Call]:
    for child in ast.iter_child_nodes(node):
        child_allowed = allowed or (
            isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
            and child.name in _CLI_FUNCS
        )
        if (
            not child_allowed
            and isinstance(child, ast.Call)
            and isinstance(child.func, ast.Name)
            and child.func.id == "print"
        ):
            yield child
        yield from _prints(child, child_allowed)


@rule(
    "DG108",
    "print-discipline",
    "print() in package code bypasses the logging spine — the record "
    "never reaches the ring, GET /logs, the job DTO tail, or a flight "
    "dump. Use a module logger; CLI entry points (cli.py, __main__.py, "
    "functions named main) are exempt.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    assert module.tree is not None
    if os.path.basename(module.relpath) in _CLI_BASENAMES:
        return
    for call in _prints(module.tree, False):
        yield Finding(
            module.relpath,
            call.lineno,
            call.col_offset,
            "DG108",
            "print() in package code never reaches the structured log "
            "ring — use `log = logging.getLogger(__name__)` so the "
            "record is queryable (or justify with a disable comment)",
        )
