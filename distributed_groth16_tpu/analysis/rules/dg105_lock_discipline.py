"""DG105 — lock discipline for ``# guarded-by:`` annotated attributes.

Shared mutable state in the service/telemetry layers is documented at
the declaration site::

    self._events = []  # guarded-by: _lock

and this rule enforces the annotation: any *mutation* of ``self._events``
(assignment, augmented assignment, ``del``, item assignment, or a
mutating method call — append/pop/update/...) anywhere in the class must
sit lexically inside ``with self._lock:``. ``__init__`` is exempt
(construction happens-before sharing). Reads are not checked — many are
intentionally racy snapshots; the annotation is about lost updates.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import Finding, Module, Project, rule

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "sort",
}


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _guarded_attrs(cls: ast.ClassDef, module: Module) -> dict[str, str]:
    """{attr: lock_attr} from `# guarded-by:` comments on `self.X = ...`
    lines anywhere in the class body (typically __init__)."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        m = _GUARD_RE.search(module.line_text(node.lineno))
        if not m:
            continue
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                out[attr] = m.group(1)
    return out


def _held_locks(module: Module, node: ast.AST, fn: ast.AST) -> set[str]:
    """Lock attrs of `self` held via `with self.X:` around `node`,
    walking ancestors up to (and excluding) the enclosing function."""
    held: set[str] = set()
    for anc in module.ancestors(node):
        if anc is fn:
            break
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    held.add(attr)
    return held


def _mutations(fn: ast.AST) -> Iterator[tuple[str, ast.AST, str]]:
    """(attr, node, how) for every mutation of a self attribute in fn."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, node, "assignment"
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, node, "item assignment"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, node, "del"
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, node, "item del"
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    yield attr, node, f".{node.func.attr}()"


@rule(
    "DG105",
    "lock-discipline",
    "An attribute annotated `# guarded-by: _lock` is mutated outside "
    "`with self._lock:` — a lost-update race under the thread pool / "
    "event-loop mix.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    assert module.tree is not None
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _guarded_attrs(cls, module)
        if not guarded:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue
            for attr, node, how in _mutations(fn):
                lock = guarded.get(attr)
                if lock is None:
                    continue
                if lock in _held_locks(module, node, fn):
                    continue
                yield Finding(
                    module.relpath,
                    node.lineno,
                    node.col_offset,
                    "DG105",
                    f"{how} of `self.{attr}` (guarded-by: {lock}) outside "
                    f"`with self.{lock}:` in {cls.name}.{fn.name}",
                )
