"""DG103 — DG16_* env-knob discipline.

One authoritative config surface: every ``DG16_*`` knob is declared in
``utils/config.py`` (the KNOBS registry) and read through its typed
accessors. A raw ``os.environ`` read anywhere else re-scatters the
config system the service/scheduler PRs centralized — and a knob nobody
documented is a knob nobody can operate. Two checks:

  (a) per-module: ``os.environ.get/[]``, ``os.getenv``, or
      ``"DG16_X" in os.environ`` with a DG16_* literal outside
      utils/config.py;
  (b) project-wide: every DG16_* literal in utils/config.py must appear
      in README.md or docs/*.md.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import Finding, Module, Project, dotted_name, rule, str_const

# THE config module, at the repo layout's two spellings (package checkout
# vs a fixture tree rooted above utils/) — deliberately not a bare
# endswith: `scheduler/myutils/config.py` must NOT inherit the exemption
_CONFIG_PATHS = (
    "utils/config.py",
    "distributed_groth16_tpu/utils/config.py",
)


def _is_config_module(relpath: str) -> bool:
    return relpath in _CONFIG_PATHS


def _env_read_key(node: ast.AST) -> str | None:
    """The string key of an environ read expression, if literal."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
            return str_const(node.args[0]) if node.args else None
    if isinstance(node, ast.Subscript):
        base = dotted_name(node.value)
        if base in ("os.environ", "environ"):
            return str_const(node.slice)
    if isinstance(node, ast.Compare):
        base = node.comparators and dotted_name(node.comparators[0])
        if (
            len(node.ops) == 1
            and isinstance(node.ops[0], (ast.In, ast.NotIn))
            and base in ("os.environ", "environ")
        ):
            return str_const(node.left)
    return None


@rule(
    "DG103",
    "env-knob discipline",
    "DG16_* environment reads outside utils/config.py (declare the knob "
    "in config.KNOBS and read it via config.env_str/env_flag/env_int/"
    "env_float), and knobs declared but documented nowhere under docs/.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    if _is_config_module(module.relpath):
        return
    assert module.tree is not None
    for node in ast.walk(module.tree):
        key = _env_read_key(node)
        if key is not None and key.startswith("DG16_"):
            yield Finding(
                module.relpath,
                node.lineno,
                node.col_offset,
                "DG103",
                f"raw environment read of {key} outside utils/config.py — "
                "declare it in config.KNOBS and read it via the typed "
                "config.env_* accessors",
            )


@rule(
    "DG103",
    "env-knob discipline",
    "(project half — declared-but-undocumented knobs)",
    project_wide=True,
)
def check_project(project: Project) -> Iterator[Finding]:
    cfg = next(
        (m for m in project.modules if _is_config_module(m.relpath)), None
    )
    if cfg is None or cfg.tree is None:
        return

    docs_text = ""
    for rel in ("README.md",):
        docs_text += project.doc_text(rel) or ""
    docs_dir = project.root / "docs"
    if docs_dir.is_dir():
        for p in sorted(docs_dir.glob("*.md")):
            try:
                docs_text += p.read_text()
            except OSError:
                pass

    seen: set[str] = set()
    for node in ast.walk(cfg.tree):
        if not (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("DG16_")
        ):
            continue
        knob = node.value
        if knob in seen:
            continue
        seen.add(knob)
        # word-boundary match: DG16_TRACE must not count as documented
        # just because DG16_TRACE_OUT has a row
        if not re.search(rf"{re.escape(knob)}(?![A-Z0-9_])", docs_text):
            yield Finding(
                cfg.relpath,
                node.lineno,
                node.col_offset,
                "DG103",
                f"knob {knob} is declared in utils/config.py but "
                "documented in neither README.md nor docs/*.md — "
                "an operator cannot discover it",
            )
