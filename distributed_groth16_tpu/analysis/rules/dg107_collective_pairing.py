"""DG107 — king/client collective pairing (static MPC deadlock detector).

The star collectives are rendezvous points: ``gather_to_king`` /
``scatter_from_king`` / ``king_compute`` / ``broadcast_from_king`` must
be entered by *every* party, and a king-side ``send_to`` must meet a
client-side ``recv_from`` on the same logical channel
(``sid`` — the MultiplexedStreamID). A function that branches on
``is_king`` and calls a symmetric collective on only one side, or whose
directional sends/recvs don't pair across the branch, hangs the whole
star until the op deadline fires — the bug class PR 1's chaos suite
catches dynamically, caught here at parse time.

Per ``if <...is_king...>`` statement (``not`` swaps the branches; an
early-``return`` king body treats the block's tail as the client side)
the rule compares the two branches' collective call multisets:

  * a symmetric collective present on one side and absent from the
    other → finding;
  * king ``send_to`` without client ``recv_from`` (and vice versa,
    king ``recv_from`` without client ``send_to``) → finding;
  * when every ``sid`` involved is a literal and no loop multiplies the
    calls, the literal ``sid`` multisets must match too.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from ..core import Finding, Module, Project, call_kw, rule

_SYMMETRIC = (
    "gather_to_king",
    "scatter_from_king",
    "king_compute",
    "broadcast_from_king",
)
_DIRECTIONAL = ("send_to", "recv_from")
# positional index of `sid` in each collective's signature
_SID_POS = {
    "send_to": 2,
    "recv_from": 1,
    "gather_to_king": 1,
    "scatter_from_king": 1,
    "king_compute": 2,
    "broadcast_from_king": 1,
}


@dataclass
class Coll:
    op: str
    sid: int | None  # literal sid, None when dynamic or defaulted-0? (0)
    line: int
    col: int
    in_loop: bool


def _sid_of(call: ast.Call, op: str) -> int | None:
    node = call_kw(call, "sid")
    pos = _SID_POS[op]
    if node is None and len(call.args) > pos:
        node = call.args[pos]
    if node is None:
        return 0  # every collective defaults sid=0
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _collect(body: list[ast.stmt], in_loop: bool = False) -> list[Coll]:
    """Collective calls in a statement list, descending into loops/with/
    try and comprehensions but not nested function defs."""
    out: list[Coll] = []

    def visit_expr(node: ast.AST, loop: bool):
        parents: dict[ast.AST, ast.AST] = {}
        for p in ast.walk(node):
            for c in ast.iter_child_nodes(p):
                parents[c] = p
        for sub in ast.walk(node):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
            ):
                continue
            op = sub.func.attr
            if op not in _SYMMETRIC and op not in _DIRECTIONAL:
                continue
            in_comp = loop
            anc = parents.get(sub)
            while anc is not None:
                if isinstance(
                    anc,
                    (ast.GeneratorExp, ast.ListComp, ast.SetComp, ast.DictComp),
                ):
                    in_comp = True
                anc = parents.get(anc)
            out.append(
                Coll(op, _sid_of(sub, op), sub.lineno, sub.col_offset, in_comp)
            )

    def visit(stmts: list[ast.stmt], loop: bool):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            is_loop = loop or isinstance(
                stmt, (ast.For, ast.AsyncFor, ast.While)
            )
            for field, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value and isinstance(
                    value[0], ast.stmt
                ):
                    visit(value, is_loop)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.excepthandler):
                            visit(v.body, is_loop)
                        elif isinstance(v, ast.AST):
                            visit_expr(v, is_loop)
                elif isinstance(value, ast.AST):
                    visit_expr(value, is_loop)

    visit(body, in_loop)
    return out


def _is_king_test(test: ast.AST) -> tuple[bool, bool]:
    """(is a king-branch test, negated)."""
    negated = False
    while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        negated = not negated
        test = test.operand
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr == "is_king":
            return True, negated
        if isinstance(sub, ast.Name) and sub.id == "is_king":
            return True, negated
    return False, False


def _terminates(body: list[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _pair_findings(
    module: Module,
    king: list[Coll],
    client: list[Coll],
    check_king: bool,
    check_client: bool,
    sid_sound: bool,
) -> Iterator[Finding]:
    """check_king / check_client say which side's calls must find a
    counterpart on the other side. Both are on when the two lists are
    genuinely exclusive (explicit else, or the tail after an early-
    returning branch); in the fall-through case only the branch side is
    checked — the "other side" then is shared code the branch's party
    also runs, so absence from the branch proves nothing, and sid
    multiset comparison (sid_sound) is off too."""

    def ops(side: list[Coll], op: str) -> list[Coll]:
        return [c for c in side if c.op == op]

    def sid_mismatch(a: list[Coll], b: list[Coll]) -> bool:
        if not sid_sound or any(c.in_loop or c.sid is None for c in a + b):
            return False
        return sorted(c.sid for c in a) != sorted(c.sid for c in b)

    for op in _SYMMETRIC:
        k, c = ops(king, op), ops(client, op)
        if k and not c and check_king:
            for call in k:
                yield Finding(
                    module.relpath, call.line, call.col, "DG107",
                    f"king-side `{op}` has no client-side `{op}` — a "
                    "symmetric collective entered by one side deadlocks "
                    "the star",
                )
        elif c and not k and check_client:
            for call in c:
                yield Finding(
                    module.relpath, call.line, call.col, "DG107",
                    f"client-side `{op}` has no king-side `{op}` — a "
                    "symmetric collective entered by one side deadlocks "
                    "the star",
                )
        elif k and c and sid_mismatch(k, c):
            yield Finding(
                module.relpath, k[0].line, k[0].col, "DG107",
                f"`{op}` sids differ between king side "
                f"({sorted(x.sid for x in k)}) and client side "
                f"({sorted(x.sid for x in c)}) — the parties rendezvous "
                "on different channels",
            )

    # directional rendezvous: king send_to <-> client recv_from and
    # king recv_from <-> client send_to
    for king_op, client_op in (("send_to", "recv_from"),
                               ("recv_from", "send_to")):
        k, c = ops(king, king_op), ops(client, client_op)
        if k and not c and check_king:
            for call in k:
                yield Finding(
                    module.relpath, call.line, call.col, "DG107",
                    f"king-side `{king_op}` has no matching client-side "
                    f"`{client_op}` — the client never meets this "
                    "point-to-point op",
                )
        elif c and not k and check_client:
            for call in c:
                yield Finding(
                    module.relpath, call.line, call.col, "DG107",
                    f"client-side `{client_op}` has no matching king-side "
                    f"`{king_op}` — the king never meets this "
                    "point-to-point op",
                )
        elif k and c and sid_mismatch(k, c):
            yield Finding(
                module.relpath, k[0].line, k[0].col, "DG107",
                f"king `{king_op}` sids {sorted(x.sid for x in k)} don't "
                f"pair with client `{client_op}` sids "
                f"{sorted(x.sid for x in c)}",
            )


def _visit_block(
    module: Module, body: list[ast.stmt], fn_calls: list[Coll]
) -> Iterator[Finding]:
    for i, stmt in enumerate(body):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested functions are analyzed on their own
        if isinstance(stmt, ast.If):
            king_test, negated = _is_king_test(stmt.test)
            if king_test:
                # side_a runs when the test is true, side_b otherwise
                side_a = _collect(stmt.body)
                exclusive = True
                if stmt.orelse:
                    side_b = _collect(stmt.orelse)
                elif _terminates(stmt.body):
                    # `if <test>: ...; return` — the block's tail is the
                    # other side's path
                    side_b = _collect(body[i + 1:])
                else:
                    # no else, no early return: both sides run the rest of
                    # the function — only branch-has/rest-lacks is sound
                    side_b = _collect_outside(fn_calls, stmt)
                    exclusive = False
                king, client = (
                    (side_b, side_a) if negated else (side_a, side_b)
                )
                # in the fall-through case only the branch side (side_a)
                # must find counterparts
                check_king = exclusive or not negated
                check_client = exclusive or negated
                yield from _pair_findings(
                    module, king, client, check_king, check_client,
                    sid_sound=exclusive,
                )
        # recurse into every nested statement block (nested ifs get their
        # own analysis at their own block level)
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value and isinstance(
                value[0], ast.stmt
            ):
                yield from _visit_block(module, value, fn_calls)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _visit_block(module, handler.body, fn_calls)


def _collect_outside(fn_calls: list[Coll], stmt: ast.If) -> list[Coll]:
    """Calls of the function that are not inside stmt's king body."""
    inside = {
        (c.line, c.col, c.op) for c in _collect(stmt.body)
    }
    return [
        c for c in fn_calls if (c.line, c.col, c.op) not in inside
    ]


@rule(
    "DG107",
    "collective-pairing",
    "Within a function branching on is_king, king-side and client-side "
    "MpcNet collective sequences (and their literal sids) must pair up — "
    "an unpaired collective is a static deadlock.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    assert module.tree is not None
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_calls = _collect(fn.body)
        yield from _visit_block(module, fn.body, fn_calls)
