"""DG104 — metric-catalog drift.

docs/OBSERVABILITY.md is the contract dashboards and alerts are built
against; a series registered in code but missing from the catalog is
invisible to operators, and a catalog row whose series no longer exists
is an alert that can never fire. This rule parses both sides:

  * code: every ``registry().counter/gauge/histogram("name", help,
    (labels...))`` call with a literal name;
  * docs: every catalog table row (4+ cells whose Type cell is
    counter/gauge/histogram; the Series cell may hold ``a`` / ``b``
    pairs).

and reports name drift in both directions plus type/label-set
mismatches.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import Finding, Module, Project, call_kw, rule, str_const

_KINDS = {"counter", "gauge", "histogram"}
_CATALOG_DOC = "docs/OBSERVABILITY.md"
_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*)`")
_LABEL_RE = re.compile(r"`([a-zA-Z_][a-zA-Z0-9_]*)`")


def _labels_from(node: ast.AST | None) -> tuple | None:
    """Literal label tuple, () for absent, None for non-literal."""
    if node is None:
        return ()
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = str_const(el)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def registrations(module: Module) -> Iterator[tuple[str, str, tuple | None, int, int]]:
    """(name, kind, labels-or-None, line, col) per metric registration."""
    assert module.tree is not None
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _KINDS
        ):
            continue
        name = str_const(node.args[0]) if node.args else None
        if name is None:
            continue
        labels_node = (
            node.args[2] if len(node.args) >= 3 else call_kw(node, "labelnames")
        )
        yield (
            name,
            node.func.attr,
            _labels_from(labels_node),
            node.lineno,
            node.col_offset,
        )


def parse_catalog(text: str) -> dict[str, tuple[str, tuple, int]]:
    """{series: (kind, labels, line)} from the markdown catalog tables."""
    out: dict[str, tuple[str, tuple, int]] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 4 or cells[1] not in _KINDS:
            continue
        labels = tuple(_LABEL_RE.findall(cells[2]))
        for name in _NAME_RE.findall(cells[0]):
            out[name] = (cells[1], labels, lineno)
    return out


@rule(
    "DG104",
    "metric-catalog drift",
    "Metric series registered in code must match the "
    "docs/OBSERVABILITY.md catalog — name, type, and label set, in both "
    "directions.",
    project_wide=True,
)
def check_project(project: Project) -> Iterator[Finding]:
    text = project.doc_text(_CATALOG_DOC)
    if text is None:
        return  # fixture trees without docs: rule is inert
    catalog = parse_catalog(text)

    registered: dict[str, tuple[str, tuple | None, str, int, int]] = {}
    for mod in project.modules:
        if mod.tree is None:
            continue
        for name, kind, labels, line, col in registrations(mod):
            registered.setdefault(name, (kind, labels, mod.relpath, line, col))

    for name, (kind, labels, relpath, line, col) in sorted(registered.items()):
        row = catalog.get(name)
        if row is None:
            yield Finding(
                relpath, line, col, "DG104",
                f"metric `{name}` is registered in code but has no row in "
                f"{_CATALOG_DOC} — add it to the catalog",
            )
            continue
        doc_kind, doc_labels, _ = row
        if doc_kind != kind:
            yield Finding(
                relpath, line, col, "DG104",
                f"metric `{name}` is a {kind} in code but a {doc_kind} in "
                f"{_CATALOG_DOC}",
            )
        if labels is not None and tuple(sorted(labels)) != tuple(
            sorted(doc_labels)
        ):
            yield Finding(
                relpath, line, col, "DG104",
                f"metric `{name}` labels {sorted(labels)} in code but "
                f"{sorted(doc_labels)} in {_CATALOG_DOC}",
            )

    for name, (_, _, lineno) in sorted(catalog.items()):
        if name not in registered:
            yield Finding(
                _CATALOG_DOC, lineno, 0, "DG104",
                f"catalog row `{name}` has no registration in the scanned "
                "code — dead series, delete the row (or lint the module "
                "that registers it)",
            )
