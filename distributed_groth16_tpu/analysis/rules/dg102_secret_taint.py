"""DG102 — secret values reaching observable sinks.

The paper's security property: no single server may learn the witness,
and the CRS trapdoor ("toxic waste") must never leave setup. The repo's
telemetry plane, flight recorder, and HTTP DTOs are all one careless
call away from shipping a share somewhere persistent. This rule flags
identifiers that *name* secret material (witness / wtns / trapdoor /
toxic / secret) flowing into:

  * logging calls (``log.debug(...)``, ``print(...)``),
  * ``tracing.span(...)`` attributes,
  * metric label values (``family.labels(...)``),
  * flight-recorder notes/dumps (``flight.note/dump/dump_soon``),
  * serialization / DTO sinks (``json.dumps``, ``json_response``),

plus the packing special case: ``pack_proving_key(...)`` without
``strip=True`` ships trapdoor-derived scalars to every party — call
sites that intentionally keep them (setup, tests) must carry a
justifying ``# dg16lint: disable=DG102`` comment.

Matching is word-based on snake/camel segments, with a small benign list
(``num_witness`` et al: sizes and module names, not values).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import Finding, Module, Project, call_kw, dotted_name, rule

_SECRET_PARTS = {"witness", "wtns", "trapdoor", "toxic", "secret"}
_EXTRA_SECRET_NAMES = {"z_mont"}  # the full witness vector, post-encode
# identifiers that contain a secret word but name sizes/machinery, not values
_BENIGN = {
    "num_witness",
    "n_witness",
    "num_wtns",
    "witness_calculator",
    "WitnessCalculator",
    "witness_calculator_py",
    "witness_generator",
    "calculate_witness",
    "witness_count",
}

_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}
_LOG_RECEIVERS = {"log", "logger", "logging"}
_FLIGHT_METHODS = {"note", "dump", "dump_soon"}
_SERIALIZE = {"json.dumps", "json_response", "web.json_response"}

_CAMEL_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _secret_identifier(name: str) -> bool:
    if name in _BENIGN:
        return False
    if name in _EXTRA_SECRET_NAMES:
        return True
    words = _CAMEL_RE.sub("_", name).lower().split("_")
    return any(w in _SECRET_PARTS for w in words)


def _secret_refs(expr: ast.AST) -> Iterator[str]:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and _secret_identifier(sub.id):
            yield sub.id
        elif isinstance(sub, ast.Attribute) and _secret_identifier(sub.attr):
            yield sub.attr


def _sink_kind(call: ast.Call) -> str | None:
    """Which sink family this call is, or None."""
    name = dotted_name(call.func)
    if name in _SERIALIZE:
        return "serialization"
    if name == "print":
        return "log"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = dotted_name(call.func.value)
        if attr in _LOG_METHODS and recv is not None and (
            recv in _LOG_RECEIVERS
            or recv.split(".")[-1] in _LOG_RECEIVERS
            or recv.endswith("log")
        ):
            return "log"
        if attr == "labels":
            return "metric label"
        if attr == "span" or (name is not None and name.endswith("tracing.span")):
            return "span attr"
        if attr in _FLIGHT_METHODS and recv is not None and (
            "flight" in recv or recv == "self"
        ):
            return "flight-recorder"
        if attr == "bind" and recv is not None and "logbus" in recv:
            # logbus.bind(tenant=...) stamps its kwargs onto every
            # subsequent ring record — a log sink in slow motion
            return "log"
    else:
        if name == "span":
            return "span attr"
        if name in _FLIGHT_METHODS:
            return "flight-recorder"
    return None


def _sink_args(call: ast.Call, kind: str) -> Iterator[ast.AST]:
    """The value expressions a sink would record."""
    if kind == "span attr":
        # span("name", party=..., attrs={...}) — the kwargs are recorded
        for kw in call.keywords:
            yield kw.value
        return
    for a in call.args:
        yield a
    for kw in call.keywords:
        yield kw.value


@rule(
    "DG102",
    "secret-taint",
    "Identifier naming witness/trapdoor/toxic-waste material flows into a "
    "log line, span attribute, metric label, flight-recorder dump, or "
    "serialization sink — the zkSaaS no-single-server-learns-the-witness "
    "property, enforced at the code layer. Also flags pack_proving_key "
    "without strip=True (trapdoor scalars shipped to every party).",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    assert module.tree is not None
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue

        # unstripped ProvingKey reaching the packing/serialization layer
        fname = dotted_name(node.func)
        if fname is not None and fname.split(".")[-1] == "pack_proving_key":
            strip = call_kw(node, "strip")
            if not (isinstance(strip, ast.Constant) and strip.value is True):
                yield Finding(
                    module.relpath,
                    node.lineno,
                    node.col_offset,
                    "DG102",
                    "pack_proving_key(...) without strip=True ships "
                    "trapdoor-derived scalars (beta/delta ext rows) to "
                    "every party — pass strip=True or justify with a "
                    "disable comment",
                )
            continue

        kind = _sink_kind(node)
        if kind is None:
            continue
        for arg in _sink_args(node, kind):
            for ident in _secret_refs(arg):
                yield Finding(
                    module.relpath,
                    node.lineno,
                    node.col_offset,
                    "DG102",
                    f"secret-named identifier `{ident}` reaches a {kind} "
                    "sink — witness/trapdoor material must never be "
                    "logged, labelled, or serialized",
                )
