"""DG101 — blocking calls inside ``async def``.

The service layer is one event loop shared by every job's admission,
heartbeats, and cancellation; a single synchronous ``time.sleep`` /
file read / ``block_until_ready`` in a coroutine stalls all of them at
once (the ProdNet heartbeat CAVEAT in utils/config.py is this failure
mode observed from the other side). Blocking work belongs behind
``asyncio.to_thread`` / ``run_in_executor`` — calls inside *nested*
(non-async) functions are exempt because closures are exactly what gets
handed to an executor.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, Module, Project, dotted_name, rule

# exact dotted names that block the loop
_EXACT = {
    "time.sleep",
    "os.system",
    "os.popen",
    "os.wait",
    "os.waitpid",
    "socket.socket",
    "socket.create_connection",
    "urllib.request.urlopen",
    "asyncio.run",
}
# any call under these module prefixes blocks
_PREFIXES = ("subprocess.", "requests.")
# method names that block regardless of receiver (device syncs, loops)
_METHODS = {"block_until_ready", "run_until_complete"}
# bare builtins that do synchronous file IO
_BUILTINS = {"open"}


def _blocking_reason(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is not None:
        if name in _EXACT:
            return name
        if any(name.startswith(p) for p in _PREFIXES):
            return name
        if name in _BUILTINS:
            return name
    if isinstance(call.func, ast.Attribute) and call.func.attr in _METHODS:
        return call.func.attr
    return None


def _own_scope_walk(fn: ast.AsyncFunctionDef) -> Iterator[ast.AST]:
    """Nodes executed in the coroutine's own frame: prune nested defs and
    lambdas (run elsewhere) but keep comprehensions and loop bodies."""
    stack: list[ast.AST] = []
    for stmt in fn.body:
        stack.append(stmt)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


@rule(
    "DG101",
    "async-blocking",
    "Blocking call (time.sleep, sync file/socket IO, subprocess, "
    "block_until_ready) directly inside an `async def` — stalls the whole "
    "event loop; wrap it in asyncio.to_thread / run_in_executor.",
)
def check(module: Module, project: Project) -> Iterator[Finding]:
    assert module.tree is not None
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in _own_scope_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            reason = _blocking_reason(sub)
            if reason is None:
                continue
            yield Finding(
                module.relpath,
                sub.lineno,
                sub.col_offset,
                "DG101",
                f"blocking call {reason}() inside `async def {node.name}` "
                "— move it to asyncio.to_thread / run_in_executor",
            )
