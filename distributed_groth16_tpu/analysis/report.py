"""Text and JSON reporters for a lint run."""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

from .baseline import fingerprints
from .core import Finding, Project


def render_text(
    new: list[Finding],
    grandfathered: list[Finding],
    stale: list[str],
    suppressed: int,
    *,
    show_grandfathered: bool = False,
) -> str:
    out: list[str] = []
    for f in new:
        out.append(f.format())
    if show_grandfathered:
        for f in grandfathered:
            out.append(f"{f.format()} [baselined]")
    counts = Counter(f.rule for f in new)
    summary = (
        f"dg16lint: {len(new)} new finding(s), "
        f"{len(grandfathered)} baselined, {suppressed} suppressed inline"
    )
    if counts:
        summary += " — " + ", ".join(
            f"{r}×{n}" for r, n in sorted(counts.items())
        )
    out.append(summary)
    if stale:
        out.append(
            f"dg16lint: {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} no longer fire — "
            "regenerate with --write-baseline"
        )
    return "\n".join(out)


def render_json(
    new: list[Finding],
    grandfathered: list[Finding],
    stale: list[str],
    suppressed: int,
    project: Project,
) -> str:
    fps = fingerprints(sorted(set(new) | set(grandfathered)), project)

    def enc(f: Finding, status: str) -> dict:
        return {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "col": f.col,
            "message": f.message,
            "fingerprint": fps[f],
            "status": status,
        }

    doc = {
        "version": 1,
        "findings": [enc(f, "new") for f in new]
        + [enc(f, "baselined") for f in grandfathered],
        "staleBaseline": sorted(stale),
        "suppressedInline": suppressed,
        "counts": {
            "new": len(new),
            "baselined": len(grandfathered),
            "byRule": dict(sorted(Counter(f.rule for f in new).items())),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def write_json(path: str, payload: str) -> None:
    if path == "-":
        print(payload)  # dg16lint: disable=DG108 — "-" means stdout
    else:
        Path(path).write_text(payload + "\n")
