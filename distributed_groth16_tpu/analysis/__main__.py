"""``python -m distributed_groth16_tpu.analysis`` entry point."""

import sys

from .cli import main

sys.exit(main())
