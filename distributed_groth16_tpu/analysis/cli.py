"""dg16lint command line.

    python -m distributed_groth16_tpu.analysis [paths...] [options]
    tools/dg16lint [paths...] [options]          # no-deps spelling

Exit codes: 0 clean (or report-only flags), 1 new findings (and, under
--strict, stale baseline entries), 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import baseline as bl
from .core import all_rules, find_root, load_project, run_rules
from .report import render_json, render_text, write_json


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dg16lint",
        description=(
            "Project-native static analysis for distributed_groth16_tpu "
            "(docs/STATIC_ANALYSIS.md has the rule catalog)."
        ),
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/dirs to lint (default: the distributed_groth16_tpu "
        "package next to the current directory)",
    )
    p.add_argument(
        "--root", default=None,
        help="project root for docs/ + baseline resolution "
        "(default: auto-detected from the first path)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <root>/{bl.DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: every finding is new",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather the current findings into the baseline and exit 0",
    )
    p.add_argument(
        "--strict", action="store_true",
        help="also fail on stale baseline entries (CI mode)",
    )
    p.add_argument(
        "--json", metavar="FILE", default=None,
        help="write the JSON report to FILE ('-' for stdout)",
    )
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--show-baselined", action="store_true",
        help="also print grandfathered findings",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def main(argv=None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        for r in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{r.id}  {r.name}")
            for line in r.doc.strip().splitlines():
                print(f"       {line.strip()}")
        return 0

    paths = [Path(p) for p in args.paths] if args.paths else None
    if paths is None:
        default = Path("distributed_groth16_tpu")
        if not default.is_dir():
            # not run from the repo root — lint the package this module
            # itself lives in (what tools/dg16lint relies on)
            default = Path(__file__).resolve().parent.parent
        paths = [default]
    for p in paths:
        if not p.exists():
            print(f"dg16lint: no such path: {p}", file=sys.stderr)
            return 2

    root = Path(args.root) if args.root else find_root(paths[0])
    project = load_project(paths, root)

    select = None
    if args.select:
        select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = select - set(all_rules()) - {"DG000"}
        if unknown:
            print(
                f"dg16lint: unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2

    findings, suppressed = run_rules(project, select)

    baseline_path = Path(args.baseline) if args.baseline else (
        root / bl.DEFAULT_BASELINE
    )
    if args.write_baseline:
        keep: list[dict] = []
        if select:
            # a --select run only saw the selected rules: retain the other
            # rules' grandfathered entries instead of wiping them
            try:
                existing = bl.load(baseline_path)
            except bl.BaselineError:
                existing = {}  # overwriting a corrupt baseline is the fix
            keep = [
                e for e in existing.values() if e.get("rule") not in select
            ]
        bl.save(baseline_path, findings, project, keep=keep)
        kept = f" (+{len(keep)} kept from unselected rules)" if keep else ""
        print(
            f"dg16lint: wrote {len(findings)} finding(s){kept} to "
            f"{baseline_path}"
        )
        if args.json:
            # snapshot of what was grandfathered, for scripted consumers
            write_json(
                args.json, render_json(findings, [], [], suppressed, project)
            )
        return 0

    try:
        baseline = {} if args.no_baseline else bl.load(baseline_path)
    except bl.BaselineError as e:
        print(f"dg16lint: {e}", file=sys.stderr)
        return 2
    if select:
        # unselected rules never ran: their entries can't be judged stale
        baseline = {
            fp: e for fp, e in baseline.items() if e.get("rule") in select
        }
    new, old, stale = bl.split(findings, project, baseline)

    print(
        render_text(
            new, old, stale, suppressed,
            show_grandfathered=args.show_baselined,
        )
    )
    if args.json:
        write_json(args.json, render_json(new, old, stale, suppressed, project))

    if new:
        return 1
    if args.strict and stale:
        return 1
    return 0
