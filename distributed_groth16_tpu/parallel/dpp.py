"""Distributed partial (prefix) products (dist-primitives/src/dpp/mod.rs:17-88):
given packed shares of num and den, returns packed shares of
num[0]/den[0], (num[0]num[1])/(den[0]den[1]), ...

Protocol: mask with preprocessed randomness s (dummy s = 1 today, as in the
reference, dpp/mod.rs:24-26), gather num||den to the king, king unpack2s,
divides, computes the prefix products in the clear (a batched
`lax.associative_scan` under Montgomery mul — log-depth instead of the
reference's sequential loop), re-packs consecutively, scatters; parties
strip s and run deg_red."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.field import fr
from .degred import deg_red
from .net import Net
from .pss import PackedSharingParams


async def d_pp(num, den, pp: PackedSharingParams, net: Net, sid: int = 0):
    """num, den: (c, 16) per-party packed share vectors."""
    F = fr()
    numden = jnp.concatenate([num, den], axis=0)  # (2c, 16)

    @jax.jit  # eager associative_scan dispatch is an XLA:CPU crash class
    def king(vals):
        x = jnp.swapaxes(jnp.stack(vals, axis=0), 0, 1)  # (2c, n, 16)
        secrets = pp.unpack2(x).reshape(-1, 16)  # (2c*l, 16) chunk-major
        half = secrets.shape[0] // 2
        nums, dens = secrets[:half], secrets[half:]
        ratio = F.mul(nums, F.inv(dens))
        prefix = jax.lax.associative_scan(F.mul, ratio, axis=0)
        out = pp.pack_from_public(prefix.reshape(-1, pp.l, 16))  # (c, n, 16)
        per_party = jnp.swapaxes(out, 0, 1)
        return [per_party[i] for i in range(pp.n)]

    masked = await net.king_compute(numden, king, sid)
    return await deg_red(masked, pp, net, sid)
