"""Radix-2 NTT "in the exponent" — FFTs directly on curve-point tensors.

The reference's group-element pack/unpack algorithm
(dist-primitives/src/dmsm/mod.rs:7-68, delegating to ark-poly's
Radix2EvaluationDomain FFT over a ProjectiveCurve): an IFFT on the share
domain followed by an FFT on the secret/secret2 coset, with every butterfly
`(lo, hi) -> (lo + w*hi, lo - w*hi)` performed on points — the twiddle
multiplication is a fixed-scalar curve multiplication.

TPU shape: each stage's lane-twiddles are FIXED Fr scalars, so a stage is
one batched fixed-scalar ladder (GLV-halved to ~129 add rounds on G1,
ops/glv.py) over the lanes plus one complete point addition. Total depth is
O(nbits * log n) versus the dense matrix ladder's O(nbits) in pss.py — but
op COUNT is O(n log n) versus O(l*n), so this path wins for large party
counts (n >= ~64, see PackedSharingParams._NTT_THRESHOLD) and exists both
as the scaling path and as algorithmic parity with the reference.

Matches ops/ntt.py JaxDomain semantics exactly (ark Radix2EvaluationDomain:
bit-reversal DIT, coset offsets, 1/n scaling in the inverse).
"""

from __future__ import annotations

import functools

import jax

import jax.numpy as jnp
import numpy as np

from ..ops.constants import FR_GENERATOR, R
from ..ops.curve import CurvePoints, fixed_scalar_ladder_tensors
from ..ops.ntt import bitrev_perm
from ..ops.refmath import finv


def fixed_scalar_mul(curve: CurvePoints, pts, tensors):
    """Per-lane fixed-scalar point multiplication.

    pts: (..., n) + point shape; tensors from fixed_scalar_ladder_tensors
    for the same lane count n. Returns the same shape:
    out[..., j] = s_j * pts[..., j].
    """
    bits, signs, nbits = tensors
    bits = jnp.asarray(bits)  # cache holds host arrays (tracer hygiene)
    signs = None if signs is None else jnp.asarray(signs)
    return _fixed_scalar_mul_jit(curve, nbits, pts, bits, signs)


# jitted: eager fori/scan dispatch is an XLA:CPU crash class here
@functools.partial(jax.jit, static_argnums=(0, 1))
def _fixed_scalar_mul_jit(curve: CurvePoints, nbits: int, pts, bits, signs):
    ax = pts.ndim - 2 - curve.coord_axes  # lane axis
    batch = pts.shape[:ax]
    base = jnp.expand_dims(pts, ax)  # (..., 1, n) + point
    if curve.glv is not None:
        base = jnp.concatenate([base, curve.endo(jnp.expand_dims(pts, ax))], axis=ax)
    acc = jnp.broadcast_to(curve.infinity(), base.shape)

    def body(i, state):
        acc, base = state
        bit = bits[..., i]  # (P, n)
        addend = base
        if signs is not None:
            addend = curve.select(signs, curve.neg(base), base)
        cand = curve.add(acc, addend)
        acc = curve.select(bit == 1, cand, acc)
        return acc, curve.double(base)

    acc, _ = jax.lax.fori_loop(0, nbits, body, (acc, base))
    # combine the GLV parts: k1*P + k2*phi(P)
    parts = acc.shape[ax]
    if parts == 1:
        return jnp.squeeze(acc, axis=ax)
    lo = jnp.take(acc, 0, axis=ax)
    hi = jnp.take(acc, 1, axis=ax)
    return curve.add(lo, hi)


class PointDomain:
    """Radix-2 evaluation domain over Fr acting on curve points."""

    def __init__(self, size: int, offset: int = 1):
        assert size > 0 and size & (size - 1) == 0
        self.size = size
        self.logn = size.bit_length() - 1
        self.offset = offset % R
        self.group_gen = pow(FR_GENERATOR, (R - 1) // size, R)
        self._perm = jnp.asarray(bitrev_perm(size))

    # host-side per-stage lane twiddles, mirroring ops/ntt.py _ntt_core
    def _stage_scalars(self, s: int, inverse: bool) -> list[int]:
        n = self.size
        out = []
        span = 1 << s
        for j in range(n):
            k = (j & (span - 1)) * (n >> (s + 1))
            if inverse:
                k = (n - k) & (n - 1)
            out.append(pow(self.group_gen, k, R))
        return out

    def _lane_scale(self, inverse: bool) -> list[int] | None:
        """Per-lane pre/post scaling: offset^i forward, (1/n)*offset^-i inverse."""
        if inverse:
            n_inv = finv(self.size, R)
            off_inv = finv(self.offset, R) if self.offset != 1 else 1
            return [n_inv * pow(off_inv, i, R) % R for i in range(self.size)]
        if self.offset == 1:
            return None
        return [pow(self.offset, i, R) for i in range(self.size)]

    def _tensors(self, curve: CurvePoints, inverse: bool):
        # cached ON the curve object, keyed by domain content (id()-keyed
        # caching could go stale across curve instance lifetimes)
        cache = curve.__dict__.setdefault("_pntt_cache", {})
        key = (self.size, self.offset, inverse)
        if key not in cache:
            # eval fence + host materialisation: first use may be inside a
            # jit/shard_map trace, and cached tracers would poison later
            # callers (same hazard as pss._ladder_tensors)
            def host(t):
                bits, signs, nbits = t
                return (jax.device_get(bits),
                        None if signs is None else jax.device_get(signs),
                        nbits)

            with jax.ensure_compile_time_eval():
                stages = [
                    host(fixed_scalar_ladder_tensors(
                        curve, self._stage_scalars(s, inverse)
                    ))
                    for s in range(self.logn)
                ]
                scale = self._lane_scale(inverse)
                scale_t = (
                    host(fixed_scalar_ladder_tensors(curve, scale))
                    if scale is not None
                    else None
                )
            cache[key] = (stages, scale_t)
        return cache[key]

    def _transform(self, curve: CurvePoints, pts, inverse: bool):
        stages, scale_t = self._tensors(curve, inverse)
        ax = pts.ndim - 2 - curve.coord_axes
        if not inverse and scale_t is not None:
            pts = fixed_scalar_mul(curve, pts, scale_t)
        x = jnp.take(pts, self._perm, axis=ax)
        n = self.size
        j = np.arange(n)
        for s in range(self.logn):
            span = 1 << s
            lo_idx = jnp.asarray(j & ~span)
            hi_idx = jnp.asarray(j | span)
            lo = jnp.take(x, lo_idx, axis=ax)
            hi = jnp.take(x, hi_idx, axis=ax)
            t = fixed_scalar_mul(curve, hi, stages[s])
            is_lo = jnp.asarray((j & span) == 0)
            t = curve.select(is_lo, t, curve.neg(t))
            x = curve.add(lo, t)
        if inverse and scale_t is not None:
            x = fixed_scalar_mul(curve, x, scale_t)
        return x

    def fft(self, curve: CurvePoints, pts):
        """Evaluate: (..., k<=n) coeff points -> (..., n) eval points."""
        return self._transform(curve, _zpad_points(curve, pts, self.size), False)

    def ifft(self, curve: CurvePoints, pts):
        """Interpolate: (..., n) eval points -> (..., n) coeff points."""
        return self._transform(curve, _zpad_points(curve, pts, self.size), True)


def _zpad_points(curve: CurvePoints, pts, n: int):
    ax = pts.ndim - 2 - curve.coord_axes
    k = pts.shape[ax]
    assert k <= n
    if k == n:
        return pts
    pad_shape = pts.shape[:ax] + (n - k,)
    inf = jnp.broadcast_to(curve.infinity(), pad_shape + (3,) + curve.elem_shape)
    return jnp.concatenate([pts, inf], axis=ax)


@functools.cache
def point_domain(size: int, offset: int = 1) -> PointDomain:
    return PointDomain(size, offset)


# -- PSS pack/unpack in the exponent via point NTTs --------------------------


def packexp_ntt(pp, curve: CurvePoints, pts):
    """(..., l) + point -> (..., n) + point: secret-coset IFFT then share FFT
    (dmsm/mod.rs:61-68)."""
    sec = point_domain(pp.secret.size, pp.secret.offset)
    sha = point_domain(pp.n)
    coeffs = sec.ifft(curve, pts)
    return sha.fft(curve, coeffs)


def unpackexp_ntt(pp, curve: CurvePoints, shares, degree2: bool):
    """(..., n) + point -> (..., l) + point: share IFFT then secret(2)-coset
    FFT, truncating like the field-side unpack/unpack2 (dmsm/mod.rs:7-48)."""
    ax = shares.ndim - 2 - curve.coord_axes
    sha = point_domain(pp.n)
    coeffs = sha.ifft(curve, shares)
    if degree2:
        sec2 = point_domain(pp.secret2.size, pp.secret2.offset)
        evals = sec2.fft(curve, coeffs)
        sl = [slice(None)] * evals.ndim
        sl[ax] = slice(0, 2 * pp.l, 2)
        return evals[tuple(sl)]
    sec = point_domain(pp.secret.size, pp.secret.offset)
    sl = [slice(None)] * coeffs.ndim
    sl[ax] = slice(0, sec.size)
    evals = sec.fft(curve, coeffs[tuple(sl)])
    sl2 = [slice(None)] * evals.ndim
    sl2[ax] = slice(0, pp.l)
    return evals[tuple(sl2)]
