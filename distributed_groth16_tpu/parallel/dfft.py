"""Distributed two-stage FFT over packed shares — hot kernel #1.

Protocol identical to the reference's d_fft/d_ifft
(dist-primitives/src/dfft/mod.rs:17-256); kernels re-designed for TPU:

  Stage 1 (every party, on device): `log m - log l` butterfly levels applied
  share-wise to the party's (m/l)-long share vector. One jitted
  `lax.fori_loop` whose body is a fully batched gather/mul/select — the
  traced graph is one butterfly regardless of m (same trick as ops/ntt.py).

  Stage 2 (king): gather all share vectors, batched-unpack every chunk
  (pp.unpack / pp.unpack2 on a (m/l, n, 16) tensor — one tiny-NTT kernel
  call), run the remaining `log l` butterfly levels + the rotate-right-by-1
  fixup in the clear, optionally zero-pad by `pad` and re-layout
  (`rearrange`) for the next transform, re-pack, scatter.

Layout contract (see parallel/packing.py): inputs arrive bit-reversed +
strided; rearrange=True produces the same layout on the (padded) output so
transforms chain; rearrange=False produces consecutive chunking.

The twiddle conventions are the reference's exactly — factor = w^(2^(i-1)*(k+1))
and the final rotate (dfft/mod.rs:142-182) — validated end-to-end against
plain `Domain.fft` ground truth, mirroring local_dfft_test.rs.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

from ..ops.field import fr
from ..ops.ntt import bitrev_perm, domain
from ..telemetry import tracing as _tracing
from .net import Net
from .pss import PackedSharingParams

log = logging.getLogger(__name__)


@functools.partial(jax.jit, static_argnames=("logm", "logl", "inverse"))
def _fft1_local(v, wpows, logm: int, logl: int, inverse: bool):
    """Stage-1 butterflies on a (..., m/l, 16) share vector.

    Level t (t = 0 .. logm-logl-1) mirrors reference level i = logm - t:
    poly_size = 2^t, butterfly partners at stride poly_size inside blocks of
    2*poly_size, twiddle w^(2^(logm-t-1) * (k+1))."""
    F = fr()
    m = 1 << logm
    mbyl = v.shape[-2]
    o = jnp.arange(mbyl, dtype=jnp.int32)

    def level(t, v):
        ps = jnp.int32(1) << t
        j = o >> (t + 1)
        k = o & (ps - 1)
        b = (o >> t) & 1
        lo = (j << (t + 1)) + k
        hi = lo + ps
        e = (k + 1) << (logm - 1 - t)
        if inverse:
            e = (m - e) & (m - 1)
        w = jnp.take(wpows, e, axis=0)
        x = jnp.take(v, lo, axis=-2)
        y = F.mul(jnp.take(v, hi, axis=-2), w)
        return jnp.where((b == 0)[:, None], F.add(x, y), F.sub(x, y))

    return jax.lax.fori_loop(0, logm - logl, level, v)


@functools.partial(jax.jit, static_argnames=("logm", "logl", "inverse"))
def _fft2_king(s, wpows, logm: int, logl: int, inverse: bool):
    """Stage-2 butterflies + rotate on the full (m, 16) clear vector.

    Level i = logl .. 1 (descending): reads pairs s[k*2^i + 2j], writes
    x+y at k*2^(i-1)+j and x-y at (k+ps)*2^(i-1)+j, twiddle
    w^(2^(i-1)*(k+1)); ends with rotate_right(1) (dfft/mod.rs:177)."""
    F = fr()
    m = 1 << logm
    o = jnp.arange(m, dtype=jnp.int32)
    half = m >> 1

    def level(t, s):
        i = jnp.int32(logl - t)
        b = (o >= half).astype(jnp.int32)
        op = o - b * half
        k = op >> (i - 1)
        j = op & ((jnp.int32(1) << (i - 1)) - 1)
        lo = (k << i) + 2 * j
        e = (k + 1) << (i - 1)
        if inverse:
            e = (m - e) & (m - 1)
        w = jnp.take(wpows, e, axis=0)
        x = jnp.take(s, lo, axis=-2)
        y = F.mul(jnp.take(s, lo + 1, axis=-2), w)
        return jnp.where((b == 0)[:, None], F.add(x, y), F.sub(x, y))

    s = jax.lax.fori_loop(0, logl, level, s)
    return jnp.roll(s, 1, axis=-2)


def _king_clear_array(
    x,
    pp: PackedSharingParams,
    logm: int,
    degree2: bool,
    inverse: bool,
    wpows,
):
    """Unpack a stacked (n, ..., m/l, 16) share tensor and run the stage-2
    butterflies in the clear: the king-side head shared by the fused
    king_clear mode of both backends. Returns (..., m, 16) natural order."""
    chunks = jnp.moveaxis(x, 0, -2)  # (..., m/l, n, 16)
    secrets = pp.unpack2(chunks) if degree2 else pp.unpack(chunks)
    s1 = secrets.reshape(secrets.shape[:-3] + (1 << logm, 16))
    return _fft2_king(s1, wpows, logm, pp.l.bit_length() - 1, inverse)


def _king_tail_array(
    x,
    pp: PackedSharingParams,
    logm: int,
    rearrange: bool,
    pad: int,
    degree2: bool,
    inverse: bool,
    wpows,
):
    """King-side tail on a stacked (n, ..., m/l, 16) share tensor ->
    (n, ..., c, 16) per-party output shares (pure function — shared by the
    async star backend and the SPMD mesh backend; extra leading batch axes
    after the party axis run as one fused transform)."""
    m = 1 << logm
    s1 = _king_clear_array(x, pp, logm, degree2, inverse, wpows)
    batch = s1.shape[:-2]
    if pad > 1:
        widths = [(0, 0)] * len(batch) + [(0, (pad - 1) * m), (0, 0)]
        s1 = jnp.pad(s1, widths)
    mp = pad * m
    c = mp // pp.l
    if rearrange:
        s1 = jnp.take(s1, jnp.asarray(bitrev_perm(mp)), axis=-2)
        out_chunks = jnp.swapaxes(
            s1.reshape(batch + (pp.l, c, 16)), -3, -2
        )
    else:
        out_chunks = s1.reshape(batch + (c, pp.l, 16))
    out_shares = pp.pack_from_public(out_chunks)  # (..., c, n, 16)
    return jnp.moveaxis(out_shares, -2, 0)  # (n, ..., c, 16)


def _king_tail(shares_list, pp, logm, rearrange, pad, degree2, inverse, wpows):
    """List-of-shares wrapper for the async star backend."""
    per_party = _king_tail_array(
        jnp.stack(shares_list, axis=0), pp, logm, rearrange, pad, degree2,
        inverse, wpows,
    )
    return [per_party[i] for i in range(pp.n)]


async def _d_transform(
    share_vec,
    rearrange: bool,
    pad: int,
    degree2: bool,
    dom,
    pp: PackedSharingParams,
    net: Net,
    sid: int,
    inverse: bool,
    king_clear: bool = False,
):
    m = dom.size
    assert share_vec.shape[-2] * pp.l == m, (
        f"Mismatch of size in FFT: {share_vec.shape[-2] * pp.l} vs {m}"
    )
    assert dom.offset == 1, "d_fft runs on plain (non-coset) domains"
    logm = m.bit_length() - 1
    logl = pp.l.bit_length() - 1
    wpows = domain(m)._live_wpows()
    F = fr()
    log.debug("d_%sfft: party %d stage-1 m=%d (sid=%d)",
              "i" if inverse else "", net.party_id, m, sid)
    with _tracing.span(
        "dfft.ifft" if inverse else "dfft.fft", party=net.party_id, sid=sid
    ):
        if inverse:
            share_vec = F.mul(share_vec, dom._size_inv)
        local = _fft1_local(share_vec, wpows, logm, logl, inverse)

        gathered = await net.gather_to_king(local, sid)
        if king_clear:
            # Fused mode: leave the clear natural-order result on the king
            # (the caller's next step is a king-side combine — re-packing
            # and scattering here would be immediately undone by a gather).
            if not net.is_king:
                return None
            return _king_clear_array(
                jnp.stack(gathered, axis=0), pp, logm, degree2, inverse, wpows
            )
        out = None
        if net.is_king:
            out = _king_tail(
                gathered, pp, logm, rearrange, pad, degree2, inverse, wpows
            )
        return await net.scatter_from_king(out, sid)


async def d_fft(
    pcoeff_share,
    rearrange: bool,
    pad: int,
    degree2: bool,
    dom,
    pp: PackedSharingParams,
    net: Net,
    sid: int = 0,
    king_clear: bool = False,
):
    """Packed shares of coefficients (bitrev+strided layout) -> packed shares
    of evaluations on `dom` (d_fft, dfft/mod.rs:17-54).

    king_clear=True skips the re-pack + scatter and returns the clear
    natural-order evaluations on the king (None on clients) — for callers
    whose next step is a king-side combine (ext_wit::h)."""
    return await _d_transform(
        pcoeff_share, rearrange, pad, degree2, dom, pp, net, sid,
        inverse=False, king_clear=king_clear,
    )


async def d_ifft(
    peval_share,
    rearrange: bool,
    pad: int,
    degree2: bool,
    dom,
    pp: PackedSharingParams,
    net: Net,
    sid: int = 0,
):
    """Packed shares of evaluations -> packed shares of coefficients
    (d_ifft, dfft/mod.rs:56-95): scale by 1/m, run with the inverse root."""
    return await _d_transform(
        peval_share, rearrange, pad, degree2, dom, pp, net, sid, inverse=True
    )
