"""Production star transport: king <-> clients over (m)TLS sockets.

The mpc-net ProdNet role (mpc-net/src/prod.rs:119-296), re-designed on
asyncio:

  * star topology — only king(0) <-> client connections (prod.rs:135-184);
  * transport-generic core over an IO-stream interface
    (new_from_pre_existing_connection genericity, prod.rs:97-117,190-243):
    `StreamIO` wraps asyncio TCP/TLS streams, `ChannelIO` is the in-memory
    fake used by tests (prod.rs:409-491), `FaultyIO` (faults.py) wraps
    either to inject faults for the chaos suite;
  * id handshake: a connecting client writes its u32 id (prod.rs:211);
  * framing: u32 big-endian length prefix (the LengthDelimitedCodec
    convention, multi.rs:26-33) around a 2-byte envelope
    (packet_type, sid) + payload. The reference multiplexes 3 real smux
    sub-streams; here the CHANNELS sub-streams are logical sid tags with
    per-(peer, sid) inbound queues — same concurrency semantics (three
    independent collectives in flight on one socket), one less protocol
    layer;
  * Syn/SynAck startup barrier (synchronize, prod.rs:246-296);
  * mTLS: king requires client certs from a pinned roster store; clients
    pin the king's cert (prod.rs:41-78). Python ssl contexts, certs from
    utils/certs.py.

Fault tolerance (see docs/ROBUSTNESS.md):
  * client dial retries with exponential backoff + jitter under a total
    startup deadline; the king's accept loop tolerates clients arriving in
    any order or re-dialing after a failed handshake, and fails fast —
    naming the missing parties — when the roster is incomplete at the
    deadline;
  * HEARTBEAT frames keep idle links observably alive; a peer silent past
    idle_timeout_s is declared dead;
  * ERR frames carry a structured abort reason; the king relays a client
    death to the other clients so the whole star fails fast instead of
    each rank discovering it by timeout;
  * any pump failure (EOF, corrupt frame, hostile sid) poisons every
    (peer, sid) queue with the reason, so pending and future recvs raise
    MpcDisconnectError instead of hanging forever.

Telemetry plane (see docs/OBSERVABILITY.md "Distributed tracing"):
  * HEARTBEAT payloads carry an NTP-style clock echo — (t_send_ns,
    echo_t0_ns, echo_rx_ns) — feeding a per-peer ClockSync so the king
    can rebase client span timestamps into its own clock;
  * TELEMETRY frames (type 5, DG16_AGG-gated) ship each client's
    compacted span buffer + metric-registry snapshot to the king at
    round boundaries and on shutdown; the king merges them into the
    process TraceAggregator with the clock offset applied;
  * fault events (peer death, ERR frames, redials) feed the flight
    recorder's ring; a peer death triggers a post-mortem dump
    (DG16_FLIGHT_DIR).

Values are serialized with utils/serde.py (the MpcSerNet typed layer) —
device arrays cross the wire as raw limb buffers.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import ssl
import struct
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..telemetry import aggregate as _agg
from ..telemetry import flight as _flight
from ..telemetry import metrics as _tm
from ..utils import serde
from ..utils.config import NetConfig
from .net import (
    CHANNELS,
    BaseNet,
    MpcDisconnectError,
    MpcNetError,
    MpcTimeoutError,
)

# connection-lifecycle tracing (the reference's env_logger role,
# mpc-net/src/prod.rs); enable via the "distributed_groth16_tpu" logger
log = logging.getLogger(__name__)

# -- network accounting ------------------------------------------------------
# Wire-level counters (docs/OBSERVABILITY.md): bytes/frames per peer and
# logical channel, heartbeat liveness, and fault events. Per-(peer, sid)
# children are pre-bound at _finish_setup so the frame path pays one dict
# lookup per send/recv; cold paths (dial retries, deaths) bind inline.
_REG = _tm.registry()
_BYTES_TX = _REG.counter(
    "net_bytes_sent_total", "Frame bytes written, per peer and channel",
    ("peer", "sid"),
)
_BYTES_RX = _REG.counter(
    "net_bytes_recv_total", "Frame bytes read, per peer and channel",
    ("peer", "sid"),
)
_FRAMES_TX = _REG.counter(
    "net_frames_sent_total", "Frames written, per peer and channel",
    ("peer", "sid"),
)
_FRAMES_RX = _REG.counter(
    "net_frames_recv_total", "Frames read, per peer and channel",
    ("peer", "sid"),
)
_HB_SENT = _REG.counter(
    "net_heartbeats_sent_total", "HEARTBEAT frames written, per peer",
    ("peer",),
)
_PEER_IDLE = _REG.gauge(
    "net_peer_idle_seconds",
    "Seconds since the last frame from peer (sampled each heartbeat tick)",
    ("peer",),
)
_RECONNECTS = _REG.counter(
    "net_reconnects_total", "Client re-dials of the king, per party",
    ("party",),
)
_ERR_FRAMES = _REG.counter(
    "net_err_frames_total", "ERR death-notice frames received, per peer",
    ("peer",),
)
_PEER_DEATHS = _REG.counter(
    "net_peer_deaths_total", "Peers declared dead, per peer", ("peer",)
)
_TLM_TX = _REG.counter(
    "telemetry_frames_sent_total",
    "TELEMETRY frames (compacted spans + metrics) written, per peer",
    ("peer",),
)
_TLM_RX = _REG.counter(
    "telemetry_frames_recv_total",
    "TELEMETRY frames received and merged, per peer",
    ("peer",),
)

SYN, SYNACK, DATA, HEARTBEAT, ERR, TELEMETRY = 0, 1, 2, 3, 4, 5

# frame overhead: u32 length prefix + (packet_type, sid) envelope
_FRAME_OVERHEAD = 6

# Frame-length ceiling: a hostile/corrupt peer must not be able to demand a
# 4 GB allocation with one u32 header (the reference bounds frames the same
# way via LengthDelimitedCodec::max_frame_length, mpc-net/src/multi.rs:26-33).
# 256 MiB comfortably clears the largest legitimate share block at million
# scale (2^20 Fr elements = 32 MiB) while bounding the damage.
MAX_FRAME_LEN = 256 << 20


class StreamIO:
    """asyncio stream pair (TCP or TLS) behind the minimal IO interface."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def read_exactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def write(self, data: bytes) -> None:
        self.writer.write(data)
        await self.writer.drain()

    async def close(self) -> None:
        self.writer.close()
        try:
            await self.writer.wait_closed()
        except Exception:  # noqa: BLE001 — peer may already be gone
            pass


class ChannelIO:
    """In-memory duplex IO over asyncio.Queues — proves the core is
    transport-generic (the reference's ChannelIO, prod.rs:409-491).
    close() delivers an EOF sentinel so a closed channel behaves like a
    closed socket (reads fail, they don't hang) — required for the
    disconnect scenarios of the chaos suite."""

    def __init__(self, inbox: asyncio.Queue, outbox: asyncio.Queue):
        self._inbox = inbox
        self._outbox = outbox
        self._buf = b""
        self._closed = False

    @staticmethod
    def pair() -> tuple["ChannelIO", "ChannelIO"]:
        a, b = asyncio.Queue(), asyncio.Queue()
        return ChannelIO(a, b), ChannelIO(b, a)

    async def read_exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = await self._inbox.get()
            if chunk is None:  # EOF from a closed peer — keep it sticky
                self._inbox.put_nowait(None)
                raise ConnectionResetError("channel closed by peer")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    async def write(self, data: bytes) -> None:
        if self._closed:
            raise ConnectionResetError("channel closed")
        await self._outbox.put(bytes(data))

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._outbox.put_nowait(None)


async def _send_frame(io, packet_type: int, sid: int, payload: bytes) -> None:
    if len(payload) + 2 > MAX_FRAME_LEN:
        raise ValueError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME_LEN; "
            "chunk the payload"
        )
    env = struct.pack("!IBB", len(payload) + 2, packet_type, sid)
    await io.write(env + payload)


async def _recv_frame(io) -> tuple[int, int, bytes]:
    (length,) = struct.unpack("!I", await io.read_exactly(4))
    if length < 2 or length > MAX_FRAME_LEN:
        raise ConnectionError(
            f"bad frame length {length} (cap {MAX_FRAME_LEN}); "
            "stream corrupt or peer hostile"
        )
    body = await io.read_exactly(length)
    return body[0], body[1], body[2:]


class ProdNet(BaseNet):
    """Star network node. Use `new_king` / `new_peer` (optionally with ssl
    contexts from utils/certs.py for mTLS) or the `from_ios` transport-
    generic constructors."""

    def __init__(
        self, party_id: int, n_parties: int,
        net_cfg: NetConfig | None = None,
    ):
        self.party_id = party_id
        self.n_parties = n_parties
        self.net_cfg = net_cfg if net_cfg is not None else NetConfig.from_env()
        self._ios: dict[int, Any] = {}  # peer id -> IO (clients: only {0})
        self._queues: dict[tuple[int, int], asyncio.Queue] = {}
        self._pumps: list[asyncio.Task] = []
        self._heartbeats: list[asyncio.Task] = []
        self._dead: set[int] = set()  # peers whose stream died
        self._death_reason: dict[int, str] = {}
        self._last_seen: dict[int, float] = {}
        self._closed = False
        # clock alignment (docs/OBSERVABILITY.md "Distributed tracing"):
        # per-peer NTP-style estimators fed by heartbeat echoes, and the
        # last heartbeat received from each peer (their_send_ns, our_rx_ns)
        # — echoed back on our next heartbeat to close the loop
        self._clocks: dict[int, _agg.ClockSync] = {}
        self._hb_rx: dict[int, tuple[int, int]] = {}
        # TELEMETRY frames held until the peer's clock offset has at
        # least one sample (bounded per peer) — merging with offset 0
        # would put another process's perf_counter epoch on our timeline
        self._pending_tlm: dict[int, list[dict]] = {}
        # king-side round close: parties (self included) that contributed
        # telemetry since the last finish_round — when every live party
        # has, the round's critical path is computed and recorded
        self._tlm_since_close: set[int] = set()
        # pre-bound per-(peer, sid) accounting children (populated in
        # _finish_setup): (bytes, frames) counter pairs per direction
        self._acct_tx: dict[tuple[int, int], tuple] = {}
        self._acct_rx: dict[tuple[int, int], tuple] = {}
        self._acct_hb: dict[int, Any] = {}
        self._acct_idle: dict[int, Any] = {}

    # -- bring-up ------------------------------------------------------------

    @classmethod
    async def new_king(
        cls,
        bind: tuple[str, int],
        n_parties: int,
        ssl_context: ssl.SSLContext | None = None,
        net_cfg: NetConfig | None = None,
    ) -> "ProdNet":
        """Accept n_parties-1 client connections, read each id handshake,
        run the Syn/SynAck barrier (prod.rs:135-157). Clients may arrive in
        any order and re-dial after a failed handshake (the newest
        connection for an id wins — the old one is presumed dead); if the
        roster is still incomplete at connect_timeout_s, raises a
        structured error naming the missing parties."""
        self = cls(0, n_parties, net_cfg)
        cfg = self.net_cfg
        accepted: dict[int, StreamIO] = {}
        done = asyncio.Event()

        async def on_conn(reader, writer):
            io = StreamIO(reader, writer)
            try:
                raw = await asyncio.wait_for(
                    io.read_exactly(4), cfg.connect_timeout_s
                )
            except Exception:  # noqa: BLE001 — half-open dial; let it re-try
                await io.close()
                return
            (cid,) = struct.unpack("!I", raw)
            if not (1 <= cid < n_parties):
                await io.close()
                return
            stale = accepted.pop(cid, None)
            if stale is not None:
                # re-dial after a handshake failure: the old connection is
                # presumed dead — replace it (mTLS pins identity, so a
                # duplicate id is the same principal, not an impostor)
                log.warning("king: party %d re-dialed; dropping stale "
                            "connection", cid)
                await stale.close()
            accepted[cid] = io
            log.debug("king: accepted party %d (%d/%d)", cid,
                      len(accepted), n_parties - 1)
            if len(accepted) == n_parties - 1:
                done.set()

        server = await asyncio.start_server(
            on_conn, bind[0], bind[1], ssl=ssl_context
        )
        try:
            await asyncio.wait_for(done.wait(), cfg.connect_timeout_s)
        except (asyncio.TimeoutError, TimeoutError):
            missing = sorted(set(range(1, n_parties)) - set(accepted))
            server.close()
            for io in accepted.values():
                await io.close()
            raise MpcTimeoutError(
                f"king: parties {missing} never connected within "
                f"{cfg.connect_timeout_s}s",
                party=0, op="new_king",
            ) from None
        # stop listening; do NOT await wait_closed() — since Python 3.12 it
        # blocks until every accepted connection closes, and ours stay open
        server.close()
        self._ios = dict(accepted)
        await self._finish_setup()
        return self

    @classmethod
    async def new_peer(
        cls,
        party_id: int,
        king_addr: tuple[str, int],
        n_parties: int,
        ssl_context: ssl.SSLContext | None = None,
        server_hostname: str | None = None,
        net_cfg: NetConfig | None = None,
    ) -> "ProdNet":
        """Dial the king with exponential backoff + jitter under the total
        connect_timeout_s deadline — a client that starts before the king
        is listening connects as soon as the king comes up."""
        assert party_id != 0
        self = cls(party_id, n_parties, net_cfg)
        cfg = self.net_cfg
        loop = asyncio.get_running_loop()
        deadline = loop.time() + cfg.connect_timeout_s
        delay = cfg.connect_base_delay_s
        attempt = 0
        while True:
            io = None
            try:
                reader, writer = await asyncio.open_connection(
                    king_addr[0],
                    king_addr[1],
                    ssl=ssl_context,
                    server_hostname=server_hostname if ssl_context else None,
                )
                io = StreamIO(reader, writer)
                await io.write(struct.pack("!I", party_id))  # id handshake
                break
            except ssl.SSLError:
                # authentication/misconfig failures are permanent: fail fast
                if io is not None:
                    await io.close()
                raise
            except OSError as e:
                # a connection whose handshake write failed must be closed
                # before the re-dial, or every backoff iteration leaks a
                # socket (and TLS session) for the whole connect window
                if io is not None:
                    await io.close()
                attempt += 1
                _RECONNECTS.labels(party=str(party_id)).inc()
                _flight.note("redial", party=party_id, attempt=attempt,
                             error=str(e))
                now = loop.time()
                if now >= deadline:
                    raise MpcTimeoutError(
                        f"party {party_id}: king at {king_addr[0]}:"
                        f"{king_addr[1]} unreachable after {attempt} "
                        f"dials over {cfg.connect_timeout_s}s "
                        f"(last error: {e})",
                        party=party_id, peer=0, op="new_peer",
                    ) from None
                sleep = min(delay, cfg.connect_max_delay_s, deadline - now)
                sleep *= 1.0 + cfg.connect_jitter * random.random()
                log.debug("party %d: dial %d failed (%s); retrying in "
                          "%.2fs", party_id, attempt, e, sleep)
                await asyncio.sleep(sleep)
                delay *= 2.0
        self._ios = {0: io}
        await self._finish_setup()
        return self

    @classmethod
    async def king_from_ios(
        cls, ios: dict[int, Any], n_parties: int,
        net_cfg: NetConfig | None = None,
    ) -> "ProdNet":
        self = cls(0, n_parties, net_cfg)
        self._ios = dict(ios)
        await self._finish_setup()
        return self

    @classmethod
    async def peer_from_io(
        cls, party_id: int, io: Any, n_parties: int,
        net_cfg: NetConfig | None = None,
    ) -> "ProdNet":
        self = cls(party_id, n_parties, net_cfg)
        self._ios = {0: io}
        await self._finish_setup()
        return self

    async def _finish_setup(self) -> None:
        loop = asyncio.get_running_loop()
        for peer, io in self._ios.items():
            p = str(peer)
            self._acct_hb[peer] = _HB_SENT.labels(peer=p)
            self._acct_idle[peer] = _PEER_IDLE.labels(peer=p)
            self._clocks[peer] = _agg.ClockSync(label=p)
            for sid in range(CHANNELS):
                self._queues[(peer, sid)] = asyncio.Queue()
                s = str(sid)
                self._acct_tx[(peer, sid)] = (
                    _BYTES_TX.labels(peer=p, sid=s),
                    _FRAMES_TX.labels(peer=p, sid=s),
                )
                self._acct_rx[(peer, sid)] = (
                    _BYTES_RX.labels(peer=p, sid=s),
                    _FRAMES_RX.labels(peer=p, sid=s),
                )
            self._last_seen[peer] = loop.time()
            self._pumps.append(asyncio.create_task(self._pump(peer, io)))
            if self.net_cfg.heartbeat_interval_s > 0:
                self._heartbeats.append(
                    asyncio.create_task(self._heartbeat(peer, io))
                )
        try:
            await self._synchronize()
        except BaseException:
            # a failed barrier must not leak pumps/heartbeats/sockets on
            # the half-built node — the caller only ever sees the error
            await self.close()
            raise

    def _account_tx(self, peer: int, sid: int, payload_len: int) -> None:
        """Count one written frame — every write path must call this so
        tx and rx accounting reconcile frame-for-frame on a healthy link
        (the pump counts the receive side)."""
        acct = self._acct_tx.get((peer, sid))
        if acct is not None:
            acct[0].inc(payload_len + _FRAME_OVERHEAD)
            acct[1].inc()

    def _now_ns(self) -> int:
        """The telemetry clock (perf_counter_ns — the span clock). A
        method so tests can subclass in a skewed clock and watch the
        estimator converge."""
        return _agg.now_ns()

    def _fail_peer(self, peer: int, reason: str, relay: bool = True) -> None:
        """Declare a peer dead: poison every (peer, sid) queue so pending
        AND future recvs raise with the reason, and — king only — relay
        the death to the other clients via ERR frames so the whole star
        fails fast instead of each rank timing out independently."""
        if peer in self._dead or self._closed:
            return
        self._dead.add(peer)
        self._death_reason[peer] = reason
        _PEER_DEATHS.labels(peer=str(peer)).inc()
        log.warning("party %d: stream to peer %d died: %s",
                    self.party_id, peer, reason)
        # PR 1's fault machinery firing is the flight recorder's trigger:
        # queue poisoning below is exactly the moment the post-mortem ring
        # still holds the lead-up (docs/OBSERVABILITY.md)
        _flight.note(
            "peer_death", party=self.party_id, peer=peer, reason=reason
        )
        _flight.dump_soon(
            "peer_death", party=self.party_id,
            extra={"peer": peer, "reason": reason},
        )
        for sid in range(CHANNELS):
            self._queues[(peer, sid)].put_nowait((None, reason))
        if relay and self.is_king:
            msg = f"king relay: party {peer} died ({reason})"
            for other, io in self._ios.items():
                if other != peer and other not in self._dead:
                    # tracked so close() can cancel an unflushed relay
                    self._pumps.append(
                        asyncio.create_task(self._send_err(other, io, msg))
                    )

    async def _send_err(self, peer: int, io, reason: str) -> None:
        try:
            payload = serde.dumps(reason)
            await _send_frame(io, ERR, 0, payload)
            self._account_tx(peer, 0, len(payload))
        except Exception:  # noqa: BLE001 — best-effort death notice
            pass

    async def _pump(self, peer: int, io) -> None:
        """Per-connection reader: route inbound frames to (peer, sid)
        queues so the logical channels never block each other. ANY failure
        (EOF, malformed frame, bad sid — the peer may be hostile) marks all
        of the peer's queues dead with a descriptive reason."""
        loop = asyncio.get_running_loop()
        try:
            while True:
                ptype, sid, payload = await _recv_frame(io)
                self._last_seen[peer] = loop.time()
                acct = self._acct_rx.get((peer, sid))
                if acct is not None:
                    acct[0].inc(len(payload) + _FRAME_OVERHEAD)
                    acct[1].inc()
                if ptype == HEARTBEAT:
                    self._on_heartbeat(peer, payload)
                    continue
                if ptype == TELEMETRY:
                    self._on_telemetry(peer, payload)
                    continue
                if ptype == ERR:
                    _ERR_FRAMES.labels(peer=str(peer)).inc()
                    _flight.note("err_frame", party=self.party_id, peer=peer)
                    try:
                        reason = serde.loads(payload)
                    except Exception:  # noqa: BLE001 — reason is best-effort
                        reason = "peer aborted (unreadable ERR payload)"
                    self._fail_peer(peer, str(reason))
                    return
                q = self._queues.get((peer, sid))
                if q is None:
                    raise MpcNetError(f"bad sid {sid} from {peer}",
                                      party=self.party_id, peer=peer)
                await q.put((ptype, payload))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — death sentinel on every failure
            self._fail_peer(peer, f"{type(e).__name__}: {e}")

    def _on_heartbeat(self, peer: int, payload: bytes) -> None:
        """Clock-echo half of the heartbeat (docs/OBSERVABILITY.md): the
        payload is (t_send_ns, echo_t0_ns, echo_rx_ns) in the sender's /
        our clock. Recording (their_send, our_rx) arms OUR next heartbeat
        to echo; a completed echo yields one (offset, rtt) sample. Empty
        or malformed payloads (pre-telemetry peers) are ignored — the
        liveness role of the frame never depends on the echo."""
        if not payload:
            return
        try:
            t_send, echo_t0, echo_rx = serde.loads(payload)
            now = self._now_ns()
            self._hb_rx[peer] = (int(t_send), now)
            if echo_t0 and echo_rx:
                off, rtt = _agg.ClockSync.from_echo(
                    int(echo_t0), int(echo_rx), int(t_send), now
                )
                self._clocks[peer].add_sample(off, rtt)
                # a clock estimate exists now: merge any TELEMETRY frames
                # that arrived before it did
                for body in self._pending_tlm.pop(peer, ()):
                    self._merge_telemetry(peer, body)
        except Exception:  # noqa: BLE001 — echo is best-effort telemetry
            pass

    def _on_telemetry(self, peer: int, payload: bytes) -> None:
        """Merge one TELEMETRY frame: the peer's compacted span events are
        rebased into OUR clock (−ClockSync.offset_ns) and handed to the
        process aggregator, with its metric snapshot alongside. Frames
        arriving with aggregation off are counted and dropped."""
        _TLM_RX.labels(peer=str(peer)).inc()
        if not _agg.enabled():
            return
        try:
            body = json.loads(serde.loads(payload))
        except Exception as e:  # noqa: BLE001 — telemetry must not kill the pump
            log.warning("party %d: unreadable TELEMETRY frame from %d: %s",
                        self.party_id, peer, e)
            return
        # No clock sample yet means timestamps are on another process's
        # perf_counter epoch — hold the frame until the heartbeat echo
        # delivers an offset (bounded; with heartbeats disabled there
        # will never be one, so merge unaligned — the in-process tests'
        # shared-clock case, where offset 0 is in fact correct).
        if (
            self.net_cfg.heartbeat_interval_s > 0
            and self._clocks[peer].n_samples == 0
        ):
            held = self._pending_tlm.setdefault(peer, [])
            held.append(body)
            del held[:-8]  # cap per peer; oldest frames drop first
            return
        self._merge_telemetry(peer, body)

    def _merge_telemetry(self, peer: int, body: dict) -> None:
        try:
            _agg.aggregator().add_party(
                peer,
                body.get("spans", []),
                offset_ns=-self._clocks[peer].offset_ns,
                metrics=body.get("metrics"),
            )
        except Exception as e:  # noqa: BLE001
            log.warning("party %d: failed to merge TELEMETRY from %d: %s",
                        self.party_id, peer, e)
            return
        self._note_tlm_contribution(peer)

    def _note_tlm_contribution(self, party: int) -> None:
        """King-side round close over the real transport: once every
        live party (dead peers excluded — a killed star must still close
        its last round) has flushed since the previous close, compute
        and record the round's critical path. The in-process backend
        closes rounds in simulate_network_round instead."""
        if not self.is_king:
            return
        self._tlm_since_close.add(party)
        live = {p for p in self._ios if p not in self._dead} | {0}
        if live <= self._tlm_since_close:
            _agg.aggregator().finish_round()
            self._tlm_since_close.clear()

    async def flush_telemetry(self) -> None:
        """Round-boundary (and shutdown) telemetry flush. Clients compact
        their aggregation buffer + a metric snapshot into one TELEMETRY
        frame to the king; the king folds its own buffer straight into
        the aggregator (client frames merge as they arrive in the pump).
        A no-op — no frame, no drain — when DG16_AGG is off."""
        if not _agg.enabled() or self._closed:
            return
        if self.is_king:
            agg = _agg.aggregator()
            for party, group in _agg.group_by_pid(_agg.drain()).items():
                agg.add_party(party, group)
            self._note_tlm_contribution(0)
            return
        # deliberately NOT gated on `0 in self._dead`: a relayed death of
        # ANOTHER party fails the star fast and marks the king dead here,
        # but this client's socket to the king is usually still healthy —
        # and a post-fault flush is exactly the post-mortem telemetry the
        # flight-recorder era wants. A genuinely dead socket just fails
        # the best-effort write below.
        io = self._ios.get(0)
        if io is None:
            return  # nothing drained: the spans keep for the next flush
        events = _agg.drain()
        payload = serde.dumps(json.dumps({
            "party": self.party_id,
            "spans": events,
            "metrics": _tm.registry().snapshot(),
        }))
        try:
            await _send_frame(io, TELEMETRY, 0, payload)
            self._account_tx(0, 0, len(payload))
            _TLM_TX.labels(peer="0").inc()
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            # the send failed but the spans need not die with it: put
            # them back so the shutdown flush (or the next round's) can
            # retry on whatever transport is left
            _agg.requeue(events)
            log.debug("party %d: telemetry flush failed: %s",
                      self.party_id, e)

    async def _heartbeat(self, peer: int, io) -> None:
        """Keepalive + liveness: send a HEARTBEAT every interval; declare
        the peer dead if nothing (data or heartbeat) arrived for
        idle_timeout_s."""
        cfg = self.net_cfg
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            if self._closed or peer in self._dead:
                return
            idle = loop.time() - self._last_seen[peer]
            self._acct_idle[peer].set(idle)
            if cfg.idle_timeout_s > 0 and idle > cfg.idle_timeout_s:
                # our own loop may just have resumed from a long
                # synchronous compute phase with the peer's frames still
                # sitting in the socket buffer: give the pump a real
                # scheduling window to drain before declaring death
                await asyncio.sleep(min(1.0, cfg.heartbeat_interval_s))
                idle = loop.time() - self._last_seen[peer]
                if idle <= cfg.idle_timeout_s:
                    continue
                self._fail_peer(
                    peer,
                    f"idle timeout: no frames from {peer} for "
                    f"{idle:.1f}s (> {cfg.idle_timeout_s}s)",
                )
                return
            try:
                # piggyback the NTP-style clock echo: our send time plus
                # the echo of the peer's last heartbeat (_on_heartbeat)
                last = self._hb_rx.get(peer)
                payload = serde.dumps((
                    self._now_ns(),
                    last[0] if last else 0,
                    last[1] if last else 0,
                ))
                await _send_frame(io, HEARTBEAT, 0, payload)
                self._acct_hb[peer].inc()
                self._account_tx(peer, 0, len(payload))
            except Exception as e:  # noqa: BLE001 — write failure = death
                self._fail_peer(peer, f"heartbeat write failed: {e}")
                return

    async def _synchronize(self) -> None:
        """Syn/SynAck barrier (prod.rs:246-296), bounded by the connect
        deadline so a peer that dialed but wedged cannot hang bring-up."""
        try:
            await asyncio.wait_for(
                self._synchronize_inner(), self.net_cfg.connect_timeout_s
            )
        except (asyncio.TimeoutError, TimeoutError):
            raise MpcTimeoutError(
                "Syn/SynAck barrier timed out",
                party=self.party_id, op="synchronize",
            ) from None

    async def _synchronize_inner(self) -> None:
        if self.is_king:
            for peer, io in self._ios.items():
                await _send_frame(io, SYN, 0, b"")
                self._account_tx(peer, 0, 0)
            for peer in self._ios:
                ptype, detail = await self._queues[(peer, 0)].get()
                if ptype != SYNACK:
                    raise MpcDisconnectError(
                        f"no SynAck from {peer} ({detail})",
                        party=0, peer=peer, op="synchronize",
                    )
        else:
            ptype, detail = await self._queues[(0, 0)].get()
            if ptype != SYN:
                raise MpcDisconnectError(
                    f"no Syn from king ({detail})",
                    party=self.party_id, peer=0, op="synchronize",
                )
            await _send_frame(self._ios[0], SYNACK, 0, b"")
            self._account_tx(0, 0, 0)

    # -- MpcNet surface ------------------------------------------------------

    async def _send_impl(self, to: int, value: Any, sid: int) -> None:
        io = self._ios.get(to)
        if io is None:
            raise MpcNetError(
                f"party {self.party_id} has no connection to {to} (star)",
                party=self.party_id, peer=to, sid=sid,
            )
        if to in self._dead:
            raise MpcDisconnectError(
                f"stream to {to} died ({self._death_reason.get(to, '?')})",
                party=self.party_id, peer=to, sid=sid,
            )
        try:
            payload = serde.dumps(_to_wire(value))
            await _send_frame(io, DATA, sid, payload)
            self._account_tx(to, sid, len(payload))
        except (ConnectionError, OSError) as e:
            self._fail_peer(to, f"send failed: {type(e).__name__}: {e}")
            raise MpcDisconnectError(
                f"stream to {to} died mid-send ({e})",
                party=self.party_id, peer=to, sid=sid,
            ) from None

    async def _recv_impl(self, frm: int, sid: int) -> Any:
        q = self._queues.get((frm, sid))
        if q is None:
            raise MpcNetError(
                f"party {self.party_id} has no connection to {frm} (star)",
                party=self.party_id, peer=frm, sid=sid,
            )
        if frm in self._dead and q.empty():
            raise MpcDisconnectError(
                f"stream from {frm} died "
                f"({self._death_reason.get(frm, '?')})",
                party=self.party_id, peer=frm, sid=sid,
            )
        ptype, payload = await q.get()
        if ptype != DATA:
            # keep the queue poisoned: every later recv must also fail,
            # not hang on an empty queue with a dead pump
            q.put_nowait((ptype, payload))
            raise MpcDisconnectError(
                f"stream from {frm} died ({payload})",
                party=self.party_id, peer=frm, sid=sid,
            )
        return _from_wire(serde.loads(payload))

    async def abort(self, reason: str) -> None:
        """Tell every live peer this party is giving up (ERR frame), then
        close — peers fail their pending recvs immediately with the reason
        instead of waiting out their deadlines."""
        for peer, io in self._ios.items():
            if peer not in self._dead:
                await self._send_err(
                    peer, io, f"party {self.party_id} aborted: {reason}"
                )
        await self.close()

    async def close(self) -> None:
        if self._closed:
            return
        # ship whatever spans the aggregation buffer still holds before
        # the sockets go away ("at round boundaries AND on shutdown")
        if _agg.enabled():
            try:
                await self.flush_telemetry()
            except Exception:  # noqa: BLE001 — closing must never fail
                pass
            # frames still held for a clock sample that never came:
            # merging unaligned beats losing the round's spans outright
            for peer, bodies in list(self._pending_tlm.items()):
                for body in bodies:
                    self._merge_telemetry(peer, body)
            self._pending_tlm.clear()
        self._closed = True
        for t in self._pumps + self._heartbeats:
            t.cancel()
        for io in self._ios.values():
            await io.close()


def _to_wire(v):
    if isinstance(v, jnp.ndarray):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        t = [_to_wire(x) for x in v]
        return t if isinstance(v, list) else tuple(t)
    return v


def _from_wire(v):
    if isinstance(v, np.ndarray):
        return jnp.asarray(v)
    if isinstance(v, (list, tuple)):
        t = [_from_wire(x) for x in v]
        return t if isinstance(v, list) else tuple(t)
    return v
