"""Production star transport: king <-> clients over (m)TLS sockets.

The mpc-net ProdNet role (mpc-net/src/prod.rs:119-296), re-designed on
asyncio:

  * star topology — only king(0) <-> client connections (prod.rs:135-184);
  * transport-generic core over an IO-stream interface
    (new_from_pre_existing_connection genericity, prod.rs:97-117,190-243):
    `StreamIO` wraps asyncio TCP/TLS streams, `ChannelIO` is the in-memory
    fake used by tests (prod.rs:409-491);
  * id handshake: a connecting client writes its u32 id (prod.rs:211);
  * framing: u32 big-endian length prefix (the LengthDelimitedCodec
    convention, multi.rs:26-33) around a 2-byte envelope
    (packet_type, sid) + payload. The reference multiplexes 3 real smux
    sub-streams; here the CHANNELS sub-streams are logical sid tags with
    per-(peer, sid) inbound queues — same concurrency semantics (three
    independent collectives in flight on one socket), one less protocol
    layer;
  * Syn/SynAck startup barrier (synchronize, prod.rs:246-296);
  * mTLS: king requires client certs from a pinned roster store; clients
    pin the king's cert (prod.rs:41-78). Python ssl contexts, certs from
    utils/certs.py.

Values are serialized with utils/serde.py (the MpcSerNet typed layer) —
device arrays cross the wire as raw limb buffers.
"""

from __future__ import annotations

import asyncio
import logging
import ssl
import struct
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..utils import serde
from .net import CHANNELS, BaseNet, MpcNetError

# connection-lifecycle tracing (the reference's env_logger role,
# mpc-net/src/prod.rs); enable via the "distributed_groth16_tpu" logger
log = logging.getLogger(__name__)

SYN, SYNACK, DATA = 0, 1, 2

# Frame-length ceiling: a hostile/corrupt peer must not be able to demand a
# 4 GB allocation with one u32 header (the reference bounds frames the same
# way via LengthDelimitedCodec::max_frame_length, mpc-net/src/multi.rs:26-33).
# 256 MiB comfortably clears the largest legitimate share block at million
# scale (2^20 Fr elements = 32 MiB) while bounding the damage.
MAX_FRAME_LEN = 256 << 20


class StreamIO:
    """asyncio stream pair (TCP or TLS) behind the minimal IO interface."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def read_exactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def write(self, data: bytes) -> None:
        self.writer.write(data)
        await self.writer.drain()

    async def close(self) -> None:
        self.writer.close()
        try:
            await self.writer.wait_closed()
        except Exception:  # noqa: BLE001 — peer may already be gone
            pass


class ChannelIO:
    """In-memory duplex IO over asyncio.Queues — proves the core is
    transport-generic (the reference's ChannelIO, prod.rs:409-491)."""

    def __init__(self, inbox: asyncio.Queue, outbox: asyncio.Queue):
        self._inbox = inbox
        self._outbox = outbox
        self._buf = b""

    @staticmethod
    def pair() -> tuple["ChannelIO", "ChannelIO"]:
        a, b = asyncio.Queue(), asyncio.Queue()
        return ChannelIO(a, b), ChannelIO(b, a)

    async def read_exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._buf += await self._inbox.get()
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    async def write(self, data: bytes) -> None:
        await self._outbox.put(bytes(data))

    async def close(self) -> None:
        pass


async def _send_frame(io, packet_type: int, sid: int, payload: bytes) -> None:
    if len(payload) + 2 > MAX_FRAME_LEN:
        raise ValueError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME_LEN; "
            "chunk the payload"
        )
    env = struct.pack("!IBB", len(payload) + 2, packet_type, sid)
    await io.write(env + payload)


async def _recv_frame(io) -> tuple[int, int, bytes]:
    (length,) = struct.unpack("!I", await io.read_exactly(4))
    if length < 2 or length > MAX_FRAME_LEN:
        raise ConnectionError(
            f"bad frame length {length} (cap {MAX_FRAME_LEN}); "
            "stream corrupt or peer hostile"
        )
    body = await io.read_exactly(length)
    return body[0], body[1], body[2:]


class ProdNet(BaseNet):
    """Star network node. Use `new_king` / `new_peer` (optionally with ssl
    contexts from utils/certs.py for mTLS) or the `from_ios` transport-
    generic constructors."""

    def __init__(self, party_id: int, n_parties: int):
        self.party_id = party_id
        self.n_parties = n_parties
        self._ios: dict[int, Any] = {}  # peer id -> IO (clients: only {0})
        self._queues: dict[tuple[int, int], asyncio.Queue] = {}
        self._pumps: list[asyncio.Task] = []
        self._dead: set[int] = set()  # peers whose stream died
        self._closed = False

    # -- bring-up ------------------------------------------------------------

    @classmethod
    async def new_king(
        cls,
        bind: tuple[str, int],
        n_parties: int,
        ssl_context: ssl.SSLContext | None = None,
    ) -> "ProdNet":
        """Accept exactly n_parties-1 client connections, read each id
        handshake, run the Syn/SynAck barrier (prod.rs:135-157)."""
        self = cls(0, n_parties)
        accepted: dict[int, StreamIO] = {}
        done = asyncio.Event()

        async def on_conn(reader, writer):
            io = StreamIO(reader, writer)
            (cid,) = struct.unpack("!I", await io.read_exactly(4))
            if not (1 <= cid < n_parties) or cid in accepted:
                await io.close()
                return
            accepted[cid] = io
            log.debug("king: accepted party %d (%d/%d)", cid,
                      len(accepted), n_parties - 1)
            if len(accepted) == n_parties - 1:
                done.set()

        server = await asyncio.start_server(
            on_conn, bind[0], bind[1], ssl=ssl_context
        )
        await done.wait()
        # stop listening; do NOT await wait_closed() — since Python 3.12 it
        # blocks until every accepted connection closes, and ours stay open
        server.close()
        self._ios = dict(accepted)
        await self._finish_setup()
        return self

    @classmethod
    async def new_peer(
        cls,
        party_id: int,
        king_addr: tuple[str, int],
        n_parties: int,
        ssl_context: ssl.SSLContext | None = None,
        server_hostname: str | None = None,
        retries: int = 50,
    ) -> "ProdNet":
        assert party_id != 0
        self = cls(party_id, n_parties)
        for attempt in range(retries):
            try:
                reader, writer = await asyncio.open_connection(
                    king_addr[0],
                    king_addr[1],
                    ssl=ssl_context,
                    server_hostname=server_hostname if ssl_context else None,
                )
                break
            except ssl.SSLError:
                # authentication/misconfig failures are permanent: fail fast
                raise
            except OSError:
                if attempt == retries - 1:
                    raise
                await asyncio.sleep(0.2)
        io = StreamIO(reader, writer)
        await io.write(struct.pack("!I", party_id))  # id handshake
        self._ios = {0: io}
        await self._finish_setup()
        return self

    @classmethod
    async def king_from_ios(
        cls, ios: dict[int, Any], n_parties: int
    ) -> "ProdNet":
        self = cls(0, n_parties)
        self._ios = dict(ios)
        await self._finish_setup()
        return self

    @classmethod
    async def peer_from_io(
        cls, party_id: int, io: Any, n_parties: int
    ) -> "ProdNet":
        self = cls(party_id, n_parties)
        self._ios = {0: io}
        await self._finish_setup()
        return self

    async def _finish_setup(self) -> None:
        for peer, io in self._ios.items():
            for sid in range(CHANNELS):
                self._queues[(peer, sid)] = asyncio.Queue()
            self._pumps.append(asyncio.create_task(self._pump(peer, io)))
        await self._synchronize()

    async def _pump(self, peer: int, io) -> None:
        """Per-connection reader: route inbound frames to (peer, sid)
        queues so the logical channels never block each other. ANY failure
        (EOF, malformed frame, bad sid — the peer may be hostile) marks all
        of the peer's queues dead."""
        try:
            while True:
                ptype, sid, payload = await _recv_frame(io)
                q = self._queues.get((peer, sid))
                if q is None:
                    raise MpcNetError(f"bad sid {sid} from {peer}")
                await q.put((ptype, payload))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — death sentinel on every failure
            log.warning("party %d: stream to peer %d died: %s",
                        self.party_id, peer, e)
            self._dead.add(peer)
            for sid in range(CHANNELS):
                self._queues[(peer, sid)].put_nowait((None, b"Stream died"))

    async def _synchronize(self) -> None:
        """Syn/SynAck barrier (prod.rs:246-296)."""
        if self.is_king:
            for peer, io in self._ios.items():
                await _send_frame(io, SYN, 0, b"")
            for peer in self._ios:
                ptype, _ = await self._queues[(peer, 0)].get()
                if ptype != SYNACK:
                    raise MpcNetError(f"no SynAck from {peer}")
        else:
            ptype, _ = await self._queues[(0, 0)].get()
            if ptype != SYN:
                raise MpcNetError("no Syn from king")
            await _send_frame(self._ios[0], SYNACK, 0, b"")

    # -- MpcNet surface ------------------------------------------------------

    async def send_to(self, to: int, value: Any, sid: int = 0) -> None:
        io = self._ios.get(to)
        if io is None:
            raise MpcNetError(
                f"party {self.party_id} has no connection to {to} (star)"
            )
        await _send_frame(io, DATA, sid, serde.dumps(_to_wire(value)))

    async def recv_from(self, frm: int, sid: int = 0) -> Any:
        q = self._queues.get((frm, sid))
        if q is None:
            raise MpcNetError(
                f"party {self.party_id} has no connection to {frm} (star)"
            )
        if frm in self._dead and q.empty():
            raise MpcNetError(f"stream from {frm} died")
        ptype, payload = await q.get()
        if ptype != DATA:
            # keep the queue poisoned: every later recv must also fail,
            # not hang on an empty queue with a dead pump
            q.put_nowait((ptype, payload))
            raise MpcNetError(f"stream from {frm} died")
        return _from_wire(serde.loads(payload))

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for t in self._pumps:
            t.cancel()
        for io in self._ios.values():
            await io.close()


def _to_wire(v):
    if isinstance(v, jnp.ndarray):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        t = [_to_wire(x) for x in v]
        return t if isinstance(v, list) else tuple(t)
    return v


def _from_wire(v):
    if isinstance(v, np.ndarray):
        return jnp.asarray(v)
    if isinstance(v, (list, tuple)):
        t = [_from_wire(x) for x in v]
        return t if isinstance(v, list) else tuple(t)
    return v
