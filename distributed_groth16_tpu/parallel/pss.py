"""Packed secret sharing (PSS) over BN254 Fr for JAX/TPU.

The sharding format of the whole framework: `l` secrets are packed into one
degree-(t+l) polynomial and dealt as `n = 4l` shares (threshold `t = l-1`),
exactly the zkSaaS scheme of the reference's secret-sharing crate
(secret-sharing/src/pss.rs:13-148):

  * shares    = evaluations on the size-n `share` domain,
  * secrets   = evaluations on a coset (offset = Fr generator) of the
                size-(l+t+1) `secret` domain,
  * products  = evaluations on the size-2(l+t+1) `secret2` coset.

pack   : IFFT on `secret` (zero-padded), FFT on `share`        (pss.rs:86-92)
unpack : IFFT on `share`, truncate to 2l coeffs, FFT on `secret`, keep l
                                                                (pss.rs:110-127)
unpack2: IFFT on `share`, FFT on `secret2`, keep even indices of the first
         2l entries                                             (pss.rs:131-148)

All field-vector transforms run batched on device via ops/ntt.py (one tiny
NTT per m/l chunk, vectorized over the chunk axis — the TPU-friendly shape).
Group-element ("in the exponent") packing for the CRS exposes the same maps
as precomputed l x n / n x l Fr matrices applied with one batched
double-and-add ladder (dist-primitives/src/dmsm/mod.rs:50-68 semantics).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import refmath as rm
from ..ops.constants import FR_GENERATOR, R
from ..ops.curve import CurvePoints, fixed_scalar_ladder_tensors
from ..ops.field import fr
from ..ops.ntt import domain


class PackedSharingParams:
    """PSS parameters and transforms for packing factor l (n = 4l parties).

    Defaults to BN254 Fr. Passing another (modulus, generator) — e.g.
    BLS12-377's — builds the HOST domains (and hence the pack/unpack
    matrices and every in-the-exponent map) over that field; the DEVICE
    field-share transforms stay BN254-only (their NTT/encode stack is
    built over ops/constants.R) and raise loudly if called.
    """

    def __init__(self, l: int, modulus: int = R,
                 generator: int = FR_GENERATOR):
        assert l >= 1 and (l & (l - 1)) == 0, "packing factor must be a power of 2"
        self.l = l
        self.t = l - 1
        self.n = 4 * l
        self.modulus = modulus
        assert self.n == 2 * (self.t + self.l + 1)
        if modulus == R:
            self.share = domain(self.n)
            self.secret = domain(self.l + self.t + 1, offset=FR_GENERATOR)
            self.secret2 = domain(
                2 * (self.l + self.t + 1), offset=FR_GENERATOR
            )
        else:
            self.share = self.secret = self.secret2 = None
        # host-side mirrors for matrix construction / ground truth
        self.share_h = rm.Domain(self.n, modulus=modulus,
                                 generator=generator)
        self.secret_h = rm.Domain(self.l + self.t + 1, offset=generator,
                                  modulus=modulus, generator=generator)
        self.secret2_h = rm.Domain(2 * (self.l + self.t + 1),
                                   offset=generator, modulus=modulus,
                                   generator=generator)

    def _device_domains(self):
        if self.share is None:
            raise NotImplementedError(
                "device field-share transforms are BN254-Fr-only; this "
                "PackedSharingParams was built over a different scalar "
                "field (pack scalars host-side, e.g. "
                "bls12_377.pack_scalars_377)"
            )
        return self.share, self.secret, self.secret2

    # -- field-vector transforms (batched over leading axes) ------------------

    def pack_from_public(self, secrets):
        """(..., l, 16) secrets -> (..., n, 16) shares."""
        assert secrets.shape[-2] == self.l
        share, secret, _ = self._device_domains()
        return share.fft(secret.ifft(secrets))

    def pack_from_public_rand(self, secrets, rng: np.random.Generator):
        """Packing with t+1 uniform-in-Fr random filler points — the hiding
        randomness of the PSS scheme (pss.rs:72-82; unlike the reference's
        test rng, fillers here are drawn uniformly from the full field)."""
        assert secrets.shape[-2] == self.l
        batch = secrets.shape[:-2]
        count = int(np.prod(batch, dtype=np.int64)) * (self.t + 1)
        # one bulk draw of 320-bit values (>=64 bits of slack over the 254-bit
        # modulus keeps the mod-R bias negligible), vectorized via frombuffer
        raw = np.frombuffer(rng.bytes(count * 40), dtype=np.uint8)
        raw = raw.reshape(count, 40)
        vals = np.empty(count, dtype=object)
        weights = [1 << (8 * i) for i in range(40)]
        cols = [raw[:, i] for i in range(40)]
        acc = np.zeros(count, dtype=object)
        for w, col in zip(weights, cols):
            acc += col.astype(object) * w
        vals = acc % R
        rand = fr().encode(vals.reshape(batch + (self.t + 1,)))
        full = jnp.concatenate([secrets, rand], axis=-2)
        share, secret, _ = self._device_domains()
        return share.fft(secret.ifft(full))

    def unpack(self, shares):
        """(..., n, 16) degree-(t+l) shares -> (..., l, 16) secrets."""
        assert shares.shape[-2] == self.n
        share, secret, _ = self._device_domains()
        coeffs = share.ifft(shares)[..., : secret.size, :]
        return secret.fft(coeffs)[..., : self.l, :]

    def unpack2(self, shares):
        """(..., n, 16) degree-2(t+l) shares -> (..., l, 16) secrets."""
        assert shares.shape[-2] == self.n
        share, _, secret2 = self._device_domains()
        coeffs = share.ifft(shares)
        evals = secret2.fft(coeffs)
        return evals[..., : 2 * self.l : 2, :]

    # -- linear maps as explicit Fr matrices (for group elements) ------------

    @functools.cached_property
    def pack_matrix(self) -> list[list[int]]:
        """(n, l) ints: shares = M @ secrets."""
        cols = []
        for i in range(self.l):
            e = [0] * self.l
            e[i] = 1
            coeffs = self.secret_h.ifft(e)
            cols.append(self.share_h.fft(coeffs))
        return [[cols[i][p] for i in range(self.l)] for p in range(self.n)]

    @functools.cached_property
    def unpack_matrix(self) -> list[list[int]]:
        """(l, n) ints: secrets = M @ shares (degree t+l shares)."""
        cols = []
        for j in range(self.n):
            e = [0] * self.n
            e[j] = 1
            coeffs = self.share_h.ifft(e)[: self.secret_h.size]
            cols.append(self.secret_h.fft(coeffs)[: self.l])
        return [[cols[j][i] for j in range(self.n)] for i in range(self.l)]

    @functools.cached_property
    def unpack2_matrix(self) -> list[list[int]]:
        """(l, n) ints: secrets = M @ shares (degree 2(t+l) shares)."""
        cols = []
        for j in range(self.n):
            e = [0] * self.n
            e[j] = 1
            coeffs = self.share_h.ifft(e)
            evals = self.secret2_h.fft(coeffs)
            cols.append(evals[: 2 * self.l : 2])
        return [[cols[j][i] for j in range(self.n)] for i in range(self.l)]

    # -- group-element ("in the exponent") transforms -------------------------
    #
    # Two implementations of the same linear maps on curve points:
    #
    #  * dense ladder (default): the (o, k) transform matrix applied in ONE
    #    fixed-scalar multi-exponentiation ladder. With the BN254 G1 GLV
    #    endomorphism (ops/glv.py) every matrix entry splits into two
    #    ~129-bit halves over the doubled base set {P, phi(P)}, so the
    #    sequential depth is 129 point-add rounds — half of plain
    #    double-and-add, and ~2x fewer than the reference's O(n log n)
    #    point-domain NTT at the deployed party counts (n <= 32), where
    #    each of the log n butterfly levels is itself a full-width ladder.
    #
    #  * point-domain NTT (parallel/pointntt.py): the reference's algorithm
    #    (dist-primitives/src/dmsm/mod.rs:7-68) — IFFT on the share domain,
    #    FFT on the secret/secret2 coset, directly on point tensors. Op
    #    count O(n log n) beats the dense O(l n) matrix only from n ~ 64
    #    parties up (each NTT level costs a full ladder of depth nbits), so
    #    `method="auto"` switches there.

    _NTT_THRESHOLD = 64

    def _ladder_tensors(self, curve: CurvePoints, which: str):
        """Device tensors (bits, signs, nbits) for the dense ladder of the
        named matrix. bits: (o, K, nbits) uint32; signs: (o, K) bool (GLV
        halves can be negative) or None; K = 2k with GLV (bases then endo
        images), k without. Cached ON the curve object keyed by matrix
        content (l, which) — id()-keyed caching would go stale if a curve
        instance were collected and its id reused."""
        cache = curve.__dict__.setdefault("_pss_ladder_cache", {})
        key = (self.l, which)
        if key in cache:
            return cache[key]
        mat = {
            "pack": self.pack_matrix,
            "unpack": self.unpack_matrix,
            "unpack2": self.unpack2_matrix,
        }[which]
        o, k = len(mat), len(mat[0])
        flat = [mat[a][b] for a in range(o) for b in range(k)]
        # ensure_compile_time_eval: this precomputation is pure-constant, but
        # first use may happen inside a jit/shard_map trace — without the
        # eval fence the cached tensors would be tracers of that trace and
        # poison every later caller (UnexpectedTracerError)
        with jax.ensure_compile_time_eval():
            bits, signs, nbits = fixed_scalar_ladder_tensors(curve, flat)
            # (P, o*k, nbits) -> per output row [part0 | part1 entries]
            P = bits.shape[0]
            bits = (
                bits.reshape(P, o, k, nbits)
                .transpose(1, 0, 2, 3)
                .reshape(o, P * k, nbits)
            )
            if signs is not None:
                signs = (
                    signs.reshape(P, o, k).transpose(1, 0, 2).reshape(o, P * k)
                )
        cache[key] = (jax.device_get(bits),
                      None if signs is None else jax.device_get(signs), nbits)
        return cache[key]

    def _apply_point_matrix(self, curve: CurvePoints, which: str, pts):
        """out[..., o, :] = sum_i mat[o][i] * pts[..., i, :].

        pts: (..., k) + point shape. One nbits-step ladder: the doubling
        chain runs on the (..., K) base set only (row-independent); the
        conditional (sign-adjusted) adds run batched over (..., o, K). Then
        a log-K tree sum over the K axis.

        Both ladder paths run under jit: eagerly-dispatched scan/fori
        executables are an XLA:CPU crash class in this environment
        (segfault in backend_compile_and_load once enough executables are
        live in the process — the class prove._maybe_mul dodged by going
        host-side; reproduced at test_pss.py:108 via eager
        sum_sequential).
        """
        bits, signs, nbits = self._ladder_tensors(curve, which)
        bits = jnp.asarray(bits)  # cache holds host arrays (tracer hygiene)
        signs = None if signs is None else jnp.asarray(signs)
        o = bits.shape[0]
        ax = pts.ndim - 2 - curve.coord_axes  # index of the k axis
        batch = pts.shape[:ax]
        base = pts
        if curve.glv is not None:
            base = jnp.concatenate([pts, curve.endo(pts)], axis=ax)
        K = base.shape[ax]

        # TPU fast path: run the ladder limb-major so every add/double in
        # the nbits-step sweep rides the Pallas kernels — CRS packing was
        # 74% of the million-2^12 wall-clock on the row-major path.
        from ..ops.msm import _tree_group

        B = int(np.prod(batch, dtype=np.int64)) if batch else 1
        g = _tree_group(curve, B * o * K)
        if g is not None:
            from ..ops.limb_kernels import ladder_apply_jit

            rm_flat = base.reshape((B * K,) + (3,) + curve.elem_shape)
            lm = g.from_rowmajor(rm_flat).reshape(g.ROWS, B, K)
            out_lm = ladder_apply_jit(g, lm, bits, signs, nbits)
            out_rm = g.to_rowmajor(out_lm.reshape(g.ROWS, B * o))
            return out_rm.reshape(batch + (o, 3) + curve.elem_shape)
        return _dense_ladder_jit(curve, ax, nbits, base, bits, signs)

    def packexp_from_public(self, curve: CurvePoints, pts, method="auto"):
        """(..., l) + point -> (..., n) + point (dmsm/mod.rs:61-68)."""
        if self._pick_exp_method(method) == "ntt":
            from .pointntt import packexp_ntt

            return packexp_ntt(self, curve, pts)
        return self._apply_point_matrix(curve, "pack", pts)

    def unpackexp(
        self, curve: CurvePoints, shares, degree2: bool = False, method="auto"
    ):
        """(..., n) + point -> (..., l) + point (dmsm/mod.rs:7-48)."""
        if self._pick_exp_method(method) == "ntt":
            from .pointntt import unpackexp_ntt

            return unpackexp_ntt(self, curve, shares, degree2)
        which = "unpack2" if degree2 else "unpack"
        return self._apply_point_matrix(curve, which, shares)

    def _pick_exp_method(self, method: str) -> str:
        if self.modulus != R:
            # pointntt's domains/twiddles are built over BN254 Fr; the
            # dense matrix ladder is the only in-exponent path for other
            # scalar fields
            if method == "ntt":
                raise NotImplementedError(
                    "in-exponent point-NTT is BN254-Fr-only; use the "
                    "dense ladder for this scalar field"
                )
            return "dense"
        if method == "auto":
            return "ntt" if self.n >= self._NTT_THRESHOLD else "dense"
        assert method in ("dense", "ntt")
        return method


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _dense_ladder_jit(curve: CurvePoints, ax: int, nbits: int,
                      base, bits, signs):
    """Row-major fixed-scalar ladder + sequential K-reduction as ONE jitted
    program (see _apply_point_matrix's crash-class note)."""
    o = bits.shape[0]
    batch = base.shape[:ax]
    K = base.shape[ax]
    acc = jnp.broadcast_to(
        curve.infinity(),
        batch + (o, K, 3) + curve.elem_shape,
    )

    def body(i, state):
        acc, b = state
        bit = bits[..., i]  # (o, K)
        addend = jnp.expand_dims(b, ax)
        if signs is not None:
            addend = curve.select(signs, curve.neg(addend), addend)
        cand = curve.add(acc, addend)
        acc = curve.select(bit == 1, cand, acc)
        return acc, curve.double(b)

    acc, _ = jax.lax.fori_loop(0, nbits, body, (acc, base))
    # K is small (<= 2n): sequential accumulation is one add instance,
    # the compile-light reduction (VERDICT r2 weak #3)
    return curve.sum_sequential(acc, axis=len(batch) + 1)


@functools.cache
def pss(l: int) -> PackedSharingParams:
    return PackedSharingParams(l)


# ---------------------------------------------------------------------------
# Host-side ground truth (pure ints) for differential tests
# ---------------------------------------------------------------------------


def pack_host(pp: PackedSharingParams, secrets: list[int]) -> list[int]:
    assert len(secrets) == pp.l
    return pp.share_h.fft(pp.secret_h.ifft(secrets))


def unpack_host(pp: PackedSharingParams, shares: list[int]) -> list[int]:
    coeffs = pp.share_h.ifft(shares)[: pp.secret_h.size]
    return pp.secret_h.fft(coeffs)[: pp.l]


def unpack2_host(pp: PackedSharingParams, shares: list[int]) -> list[int]:
    coeffs = pp.share_h.ifft(shares)
    return pp.secret2_h.fft(coeffs)[: 2 * pp.l : 2]
