"""Fault injection for the star transport — the chaos-testing harness.

`FaultyIO` wraps any object with the minimal IO interface of prodnet.py
(`read_exactly` / `write` / `close`: StreamIO, ChannelIO, or another
FaultyIO) and injects transport faults on a deterministic, seeded
schedule. The chaos suite (tests/test_faults.py) uses it to prove every
failure mode surfaces as a structured MpcNetError within its deadline —
no hangs, no silent corruption.

Faults are keyed by *write index*: prodnet frames each cross the wire as
exactly one `write()` call (length prefix + envelope + payload), so write
#i is frame #i and the length prefix is bytes [0, 4) of that write. This
makes scripted faults line up with protocol frames without the wrapper
having to parse them.

Supported faults:
  * delay    — seeded random sleep before any read/write (delay_p /
               max_delay_s): latency jitter that must stay under op
               deadlines.
  * drop     — writes from `drop_writes_from` on are swallowed: the peer
               sees silence (deadline / idle-timeout territory).
  * truncate — write `truncate_write_at` sends only half its bytes, then
               the connection behaves disconnected: the peer sees a
               partial frame then EOF.
  * corrupt  — write `corrupt_len_at` has its 4-byte length prefix
               overwritten with an over-cap value: the peer's framing
               layer must reject it without allocating.
  * disconnect — from `disconnect_write_at` / `disconnect_read_at` on,
               ops raise ConnectionResetError and the inner IO is closed:
               a mid-collective crash.
"""

from __future__ import annotations

import asyncio
import random
import struct


class FaultyIO:
    """Deterministic fault-injecting wrapper over a prodnet IO object."""

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        delay_p: float = 0.0,
        max_delay_s: float = 0.01,
        drop_writes_from: int | None = None,
        truncate_write_at: int | None = None,
        corrupt_len_at: int | None = None,
        disconnect_write_at: int | None = None,
        disconnect_read_at: int | None = None,
    ):
        self.inner = inner
        self._rng = random.Random(seed)
        self.delay_p = delay_p
        self.max_delay_s = max_delay_s
        self.drop_writes_from = drop_writes_from
        self.truncate_write_at = truncate_write_at
        self.corrupt_len_at = corrupt_len_at
        self.disconnect_write_at = disconnect_write_at
        self.disconnect_read_at = disconnect_read_at
        self.writes = 0  # frames attempted (faulted or not)
        self.reads = 0
        self._disconnected = False

    async def _maybe_delay(self) -> None:
        if self.delay_p > 0 and self._rng.random() < self.delay_p:
            await asyncio.sleep(self._rng.random() * self.max_delay_s)

    async def _disconnect(self) -> None:
        if not self._disconnected:
            self._disconnected = True
            await self.inner.close()  # peer sees EOF, not silence

    @staticmethod
    def _hit(mark: int | None, index: int) -> bool:
        return mark is not None and index == mark

    def _from(self, mark: int | None, index: int) -> bool:
        return mark is not None and index >= mark

    async def read_exactly(self, n: int) -> bytes:
        i = self.reads
        self.reads += 1
        if self._disconnected or self._from(self.disconnect_read_at, i):
            await self._disconnect()
            raise ConnectionResetError("fault injection: read disconnect")
        await self._maybe_delay()
        return await self.inner.read_exactly(n)

    async def write(self, data: bytes) -> None:
        i = self.writes
        self.writes += 1
        if self._disconnected or self._from(self.disconnect_write_at, i):
            await self._disconnect()
            raise ConnectionResetError("fault injection: write disconnect")
        await self._maybe_delay()
        if self._from(self.drop_writes_from, i):
            return  # swallowed: the peer sees silence
        if self._hit(self.truncate_write_at, i):
            await self.inner.write(data[: max(1, len(data) // 2)])
            await self._disconnect()
            return
        if self._hit(self.corrupt_len_at, i):
            # hostile/garbage length prefix, over the frame cap
            data = struct.pack("!I", 0xFFFFFFFF) + bytes(data[4:])
        await self.inner.write(data)

    async def close(self) -> None:
        await self.inner.close()
