"""SPMD mesh backend: MPC parties as shards of a jax.sharding.Mesh.

The TPU-native re-imagining of the reference's star topology for the
intra-slice case (SURVEY §2.1 "TPU equivalent"): inside one TPU slice the
n parties are shards along a "parties" mesh axis and the three star
collectives become XLA collectives over ICI —

  gather_to_king    -> lax.all_gather (every shard receives all shares)
  king computes     -> every shard runs the tiny king tail REDUNDANTLY
                       (cheaper than idling n-1 shards and avoids a
                       scatter; identical results by determinism)
  scatter_from_king -> each shard slices its own row by lax.axis_index

The whole proving round (h-poly FFTs + the A/B/C MSMs) is ONE jitted
shard_map program: no host round-trips, XLA overlaps the independent
pipelines that the async star backend runs on channels 0/1/2.

Privacy note: in-mesh mode all shards live in one trust domain (a single
TPU worker), so "king sees clear values" == "the worker sees clear values",
exactly the reference's king-node model. Cross-trust-domain deployments use
the async star backend over real transport instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:  # jax>=0.4.35 moved shard_map out of experimental
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

from ..ops.field import fr
from ..telemetry.compile import timed_jit
from .dfft import _fft1_local, _king_clear_array, _king_tail_array
from .pss import PackedSharingParams

AXIS = "parties"


def mesh_jit(fn_name: str, fn):
    """jit a mesh program with compile-cost telemetry: the first call per
    argument signature lands in `compile_seconds{fn}` and the hit/miss
    counters (telemetry/compile.py) — the m=32768 prover is compile-bound
    on some backends (VERDICT), and this makes that a measured number
    instead of folklore. Use for every whole-mesh jitted entry point."""
    return timed_jit(fn_name, jax.jit(fn))


def make_mesh(n_parties: int) -> Mesh:
    devs = np.array(jax.devices()[:n_parties])
    if len(devs) < n_parties:
        raise RuntimeError(
            f"need {n_parties} devices, have {len(jax.devices())}"
        )
    return Mesh(devs, (AXIS,))


def make_mesh_from_devices(devices) -> Mesh:
    """A parties mesh over an EXPLICIT device slice — the scheduler's
    placement layer (scheduler/placement.py) partitions the inventory into
    disjoint slices so independent batches prove concurrently instead of
    serializing through jax.devices()[:n]."""
    devs = np.array(list(devices))
    if devs.size == 0:
        raise RuntimeError("empty device slice")
    return Mesh(devs, (AXIS,))


def _own_row(stacked):
    """Per-shard slice of a replicated (n, ...) tensor -> (1, ...)."""
    idx = jax.lax.axis_index(AXIS)
    return jax.lax.dynamic_slice_in_dim(stacked, idx, 1, axis=0)


def _mesh_dfft(
    x,
    pp: PackedSharingParams,
    logm: int,
    inverse: bool,
    rearrange: bool,
    pad: int,
    degree2: bool,
    king_clear: bool,
    wpows,
    size_inv,
):
    """x: (1, ..., m/l, 16) own share block (extra axes batch independent
    transforms). Returns (1, ..., c, 16) shares, or the replicated clear
    (..., m, 16) when king_clear."""
    F = fr()
    logl = pp.l.bit_length() - 1
    if inverse:
        x = F.mul(x, size_inv)
    local = _fft1_local(x, wpows, logm, logl, inverse)
    allg = jax.lax.all_gather(local, AXIS, axis=0, tiled=True)  # (n, ..., m/l, 16)
    if king_clear:
        return _king_clear_array(allg, pp, logm, degree2, inverse, wpows)
    out = _king_tail_array(
        allg, pp, logm, rearrange, pad, degree2, inverse, wpows
    )
    return _own_row(out)


def _mesh_dmsm(curve, bases_block, scalar_block, pp: PackedSharingParams):
    """bases: (1, c, 3)+elem, scalars: (1, c, 16) Montgomery ->
    replicated clear (3,)+elem group element."""
    return _mesh_dmsm_batched(
        curve, bases_block[:, None], scalar_block[:, None], pp
    )[0]


def _mesh_dmsm_batched(curve, bases_block, scalar_block, pp: PackedSharingParams):
    """B independent d_msms of identical length in ONE traced program.

    bases: (1, B, c, 3)+elem, scalars: (1, B, c, 16) Montgomery ->
    replicated clear (B, 3)+elem. Batching is the compile-time lever: each
    distinct curve-op instantiation costs seconds of XLA:CPU compile
    (VERDICT r2 weak #3), so the prover's three same-length G1 MSMs share
    one ladder instead of instantiating three.
    """
    from ..ops.msm import msm_batched

    F = fr()
    std = F.from_mont(scalar_block[0])  # (B, c, 16)
    local = msm_batched(curve, bases_block[0], std)  # (B,)+point
    allg = jax.lax.all_gather(local, AXIS, axis=0, tiled=False)  # (n, B)+pt
    allg = jnp.moveaxis(allg, 0, 1)  # (B, n)+pt
    partials = pp.unpackexp(curve, allg, degree2=True)  # (B, l)+pt
    return curve.sum_sequential(partials, axis=1)  # (B,)+pt
