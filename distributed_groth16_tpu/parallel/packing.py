"""Share-vector layout helpers: the n x (m/l) <-> (m/l) x n reshapes and the
two chunking conventions of the reference (dist-primitives/src/utils/pack.rs
pack_vec/transpose; strided layout per groth16/src/qap.rs:143-187 and
dist-primitives/examples/local_dfft_test.rs).

Layouts over a clear vector s of length m (l secrets per share, c = m/l
chunks):

  * consecutive ("pack_vec"): chunk i = s[i*l .. (i+1)*l]
  * strided + bit-reversed ("qap/dfft layout"): first bit-reverse s, then
    chunk i = s_rev[i], s_rev[i+c], s_rev[i+2c], ...

Both pack each chunk with PSS and transpose to per-party share vectors of
shape (n, c, 16). Everything is batched device code.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.ntt import bitrev_perm
from .pss import PackedSharingParams


def pack_consecutive(pp: PackedSharingParams, vec: jnp.ndarray) -> jnp.ndarray:
    """(m, 16) clear vector -> (n, m/l, 16) per-party shares, consecutive
    chunking (pack_vec + transpose)."""
    m = vec.shape[0]
    assert m % pp.l == 0
    chunks = vec.reshape(m // pp.l, pp.l, 16)
    shares = pp.pack_from_public(chunks)  # (c, n, 16)
    return jnp.swapaxes(shares, 0, 1)


def pack_strided(pp: PackedSharingParams, vec: jnp.ndarray) -> jnp.ndarray:
    """(m, 16) clear vector -> (n, m/l, 16) per-party shares in the
    bit-reversed strided layout every d_fft/d_ifft input uses."""
    m = vec.shape[0]
    assert m % pp.l == 0
    c = m // pp.l
    x = jnp.take(vec, jnp.asarray(bitrev_perm(m)), axis=0)
    chunks = jnp.swapaxes(x.reshape(pp.l, c, 16), 0, 1)  # chunk i slot j = x[i + j*c]
    shares = pp.pack_from_public(chunks)  # (c, n, 16)
    return jnp.swapaxes(shares, 0, 1)


def unpack_shares(
    pp: PackedSharingParams, shares: jnp.ndarray, degree2: bool = False
) -> jnp.ndarray:
    """(n, c, 16) per-party shares -> (c*l, 16) clear vector in chunk-major
    order (element i*l + j = secret j of chunk i)."""
    chunks = jnp.swapaxes(shares, 0, 1)  # (c, n, 16)
    secrets = pp.unpack2(chunks) if degree2 else pp.unpack(chunks)  # (c, l, 16)
    return secrets.reshape(-1, 16)
