"""Transport-agnostic star-topology collectives ("the NCCL layer").

Re-imagines the reference's mpc-net crate (mpc-net/src/lib.rs:37-155) for the
TPU build. The collective vocabulary is exactly the reference's three
primitives plus point-to-point sends:

  * gather_to_king    — client_send_or_king_receive (lib.rs:61-99): every
                        party contributes one value; the king gets the full
                        list ordered by party id (own value included), clients
                        get None.
  * scatter_from_king — client_receive_or_king_send (lib.rs:102-139): king
                        provides one value per party (keeps its own), clients
                        receive theirs.
  * king_compute      — fused gather -> f on king -> scatter (lib.rs:146-155).

Three logical channels (CHANNELS = 3, mirroring MultiplexedStreamID::
{Zero,One,Two}, lib.rs:28-33) let three independent collectives overlap —
the a/b/c FFT pipelines and the W/U/H MSMs of the prover.

Unlike the reference, values are arbitrary Python objects (typically JAX
arrays or pytrees of them): the typed-serialization layer (dist-primitives'
MpcSerNet) is only needed at a real process boundary and lives with the
gRPC/TLS transport; in-process backends hand device buffers over directly —
zero-copy, no host round-trip.

Fault tolerance: every collective takes a per-op `timeout=` (falling back to
the net's NetConfig.op_timeout_s) and raises a structured MpcNetError —
MpcTimeoutError / MpcDisconnectError carrying (party, peer, sid, op, and —
when proving a service job — the job's correlation id) — instead of
hanging on a dead or silent peer. See docs/ROBUSTNESS.md.

Telemetry: every collective records a per-op latency sample
(collective_seconds{op=}) and, when tracing is active, a net.* span;
deadline expiries and round retries/failures increment counters. See
docs/OBSERVABILITY.md.

Backends:
  * LocalSimNet — n asyncio tasks + in-memory queues, the LocalTestNet /
    ChannelIO analog (mpc-net/src/multi.rs:227, prod.rs:409-491) used by all
    distributed tests. Harness: `simulate_network_round` (multi.rs:289-316);
    `run_round_with_retries` re-runs a round on transient transport faults.
  * ProdNet (prodnet.py) — the TLS star over real sockets, with reconnect
    backoff, heartbeats, and frame-level fault detection.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import time
from contextlib import contextmanager
from typing import Any, Awaitable, Callable, Protocol, Sequence

from ..telemetry import aggregate as _agg
from ..telemetry import flight as _flight
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tracing
from ..utils.config import NetConfig

# module-level tracing, the role of the reference's log/env_logger calls
# throughout mpc-net (multi.rs:149,:182); enable with
# logging.getLogger("distributed_groth16_tpu").setLevel(logging.DEBUG)
log = logging.getLogger(__name__)

CHANNELS = 3

# -- telemetry ---------------------------------------------------------------
# Per-op latency histograms and fault counters (docs/OBSERVABILITY.md).
# Children are pre-bound at import: the per-call cost on the collectives'
# hot path is one dict lookup + an in-place add, no allocations.
_REG = _tm.registry()
_COLLECTIVE_SECONDS = _REG.histogram(
    "collective_seconds",
    "Latency of one star collective, per op",
    ("op",),
)
_COLL = {
    op: _COLLECTIVE_SECONDS.labels(op=op)
    for op in (
        "send_to", "recv_from", "gather_to_king", "scatter_from_king",
        "king_compute",
    )
}
_TIMEOUTS = _REG.counter(
    "net_timeouts_total", "Collective deadline expiries, per op", ("op",)
)
_TO = {op: _TIMEOUTS.labels(op=op) for op in ("send_to", "recv_from")}
_ROUND_RETRIES = _REG.counter(
    "net_round_retries_total",
    "MPC rounds re-run after a transient transport fault",
)
_ROUND_FAILURES = _REG.counter(
    "net_round_failures_total",
    "MPC rounds abandoned after exhausting retries",
)

# The job the current dynamic extent is proving for, threaded by the
# service layer (service/worker.py) so a transport failure deep inside a
# collective names the job that died. Contextvars flow into asyncio tasks
# and to_thread, so one `with job_context(id):` around the round suffices.
CURRENT_JOB_ID: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "dg16_job_id", default=None
)


@contextmanager
def job_context(job_id: str | None):
    """Label every MpcNetError raised in this extent with `job_id`."""
    token = CURRENT_JOB_ID.set(job_id)
    try:
        yield
    finally:
        CURRENT_JOB_ID.reset(token)


class MpcNetError(RuntimeError):
    """Structured transport failure: names the local party, the peer the
    op was against, the logical channel, the collective — and, when raised
    while proving a service job (job_context), the job's correlation id,
    so a failed 2^20 proving round says *which* socket broke and *which*
    job died, not just that one did."""

    def __init__(
        self,
        msg: str,
        *,
        party: int | None = None,
        peer: int | None = None,
        sid: int | None = None,
        op: str | None = None,
        job_id: str | None = None,
    ):
        self.party = party
        self.peer = peer
        self.sid = sid
        self.op = op
        self.job_id = job_id if job_id is not None else CURRENT_JOB_ID.get()
        ctx = ", ".join(
            f"{k}={v}"
            for k, v in (
                ("party", party), ("peer", peer), ("sid", sid), ("op", op),
                ("job", self.job_id),
            )
            if v is not None
        )
        super().__init__(f"{msg} [{ctx}]" if ctx else msg)
        self.msg = msg

    def with_op(self, op: str) -> "MpcNetError":
        """Same failure, re-labelled with the enclosing collective."""
        return type(self)(
            self.msg, party=self.party, peer=self.peer, sid=self.sid, op=op,
            job_id=self.job_id,
        )


class MpcTimeoutError(MpcNetError):
    """An op exceeded its configured deadline (peer alive but silent)."""


class MpcDisconnectError(MpcNetError):
    """The peer's stream died (EOF, corrupt frame, reported failure)."""


class Net(Protocol):
    """The MpcNet-shaped async interface every distributed kernel takes."""

    party_id: int
    n_parties: int

    @property
    def is_king(self) -> bool: ...

    async def send_to(
        self, to: int, value: Any, sid: int = 0,
        timeout: float | None = None,
    ) -> None: ...

    async def recv_from(
        self, frm: int, sid: int = 0, timeout: float | None = None
    ) -> Any: ...

    async def gather_to_king(
        self, value: Any, sid: int = 0, timeout: float | None = None
    ): ...

    async def scatter_from_king(
        self, values, sid: int = 0, timeout: float | None = None
    ): ...


class BaseNet:
    """Collectives implemented over send_to/recv_from (as in the reference,
    where they are trait default methods). Subclasses implement
    `_send_impl` / `_recv_impl`; the deadline + structured-error wrapping
    lives here so every backend gets it for free."""

    party_id: int
    n_parties: int
    net_cfg: NetConfig | None = None

    @property
    def is_king(self) -> bool:
        return self.party_id == 0

    async def _send_impl(self, to: int, value: Any, sid: int) -> None:
        raise NotImplementedError

    async def _recv_impl(self, frm: int, sid: int) -> Any:
        raise NotImplementedError

    def _resolve_timeout(self, timeout: float | None) -> float | None:
        """Per-op override > config default; <= 0 means no deadline."""
        if timeout is None and self.net_cfg is not None:
            timeout = self.net_cfg.op_timeout_s
        if timeout is not None and timeout <= 0:
            return None
        return timeout

    async def send_to(
        self, to: int, value: Any, sid: int = 0,
        timeout: float | None = None,
    ) -> None:
        t = self._resolve_timeout(timeout)
        t0 = time.perf_counter()
        try:
            if t is None:
                await self._send_impl(to, value, sid)
            else:
                await asyncio.wait_for(self._send_impl(to, value, sid), t)
        except (asyncio.TimeoutError, TimeoutError):
            _TO["send_to"].inc()
            raise MpcTimeoutError(
                f"send deadline ({t}s) exceeded",
                party=self.party_id, peer=to, sid=sid, op="send_to",
            ) from None
        finally:
            _COLL["send_to"].observe(time.perf_counter() - t0)

    async def recv_from(
        self, frm: int, sid: int = 0, timeout: float | None = None
    ) -> Any:
        t = self._resolve_timeout(timeout)
        t0 = time.perf_counter()
        try:
            if t is None:
                return await self._recv_impl(frm, sid)
            return await asyncio.wait_for(self._recv_impl(frm, sid), t)
        except (asyncio.TimeoutError, TimeoutError):
            _TO["recv_from"].inc()
            raise MpcTimeoutError(
                f"recv deadline ({t}s) exceeded",
                party=self.party_id, peer=frm, sid=sid, op="recv_from",
            ) from None
        finally:
            _COLL["recv_from"].observe(time.perf_counter() - t0)

    async def gather_to_king(
        self, value: Any, sid: int = 0, timeout: float | None = None
    ):
        """King returns [v_0, ..., v_{n-1}] (own value at index 0);
        clients send and return None."""
        t0 = time.perf_counter()
        with _tracing.span("net.gather_to_king", party=self.party_id, sid=sid):
            try:
                return await self._gather_impl(value, sid, timeout)
            except MpcNetError as e:
                raise e.with_op("gather_to_king") from None
            finally:
                _COLL["gather_to_king"].observe(time.perf_counter() - t0)

    async def _gather_impl(self, value, sid, timeout):
        if self.is_king:
            log.debug("gather_to_king: king collecting %d values (sid=%d)",
                      self.n_parties, sid)
            out = [value]
            recvs = [
                asyncio.create_task(self.recv_from(i, sid, timeout=timeout))
                for i in range(1, self.n_parties)
            ]
            try:
                out.extend(await asyncio.gather(*recvs))
            except BaseException:
                # reap the sibling recvs: a leaked task would consume
                # a healthy peer's NEXT frame and desync later
                # collectives (or raise into the void at its deadline)
                for t in recvs:
                    t.cancel()
                await asyncio.gather(*recvs, return_exceptions=True)
                raise
            return out
        log.debug("gather_to_king: party %d sending (sid=%d)",
                  self.party_id, sid)
        await self.send_to(0, value, sid, timeout=timeout)
        return None

    async def scatter_from_king(
        self, values, sid: int = 0, timeout: float | None = None
    ):
        """King passes one value per party (or None if client); every party
        returns its own value."""
        if self.is_king:
            if values is None:
                raise MpcNetError("scatter_from_king: king must provide values")
            if len(values) != self.n_parties:
                raise MpcNetError(
                    f"scatter_from_king: {len(values)} values for "
                    f"{self.n_parties} parties"
                )
        t0 = time.perf_counter()
        with _tracing.span(
            "net.scatter_from_king", party=self.party_id, sid=sid
        ):
            try:
                return await self._scatter_impl(values, sid, timeout)
            except (MpcTimeoutError, MpcDisconnectError) as e:
                raise e.with_op("scatter_from_king") from None
            finally:
                _COLL["scatter_from_king"].observe(time.perf_counter() - t0)

    async def _scatter_impl(self, values, sid, timeout):
        if self.is_king:
            log.debug("scatter_from_king: king fanning out %d values "
                      "(sid=%d)", len(values), sid)
            sends = [
                asyncio.create_task(
                    self.send_to(i, values[i], sid, timeout=timeout)
                )
                for i in range(1, self.n_parties)
            ]
            try:
                await asyncio.gather(*sends)
            except BaseException:
                for t in sends:
                    t.cancel()
                await asyncio.gather(*sends, return_exceptions=True)
                raise
            return values[0]
        if values is not None:
            raise MpcNetError("scatter_from_king: client must pass None")
        return await self.recv_from(0, sid, timeout=timeout)

    async def king_compute(
        self,
        value: Any,
        f: Callable[[list], list],
        sid: int = 0,
        timeout: float | None = None,
    ):
        """gather -> f on king -> scatter (MpcNet::king_compute)."""
        t0 = time.perf_counter()
        with _tracing.span("net.king_compute", party=self.party_id, sid=sid):
            try:
                gathered = await self.gather_to_king(value, sid, timeout=timeout)
                out = f(gathered) if gathered is not None else None
                return await self.scatter_from_king(out, sid, timeout=timeout)
            finally:
                _COLL["king_compute"].observe(time.perf_counter() - t0)

    async def broadcast_from_king(
        self, value: Any, sid: int = 0, timeout: float | None = None
    ):
        """King's value to everyone (the d_msm result fan-out,
        dmsm/mod.rs:94-97)."""
        vals = [value] * self.n_parties if self.is_king else None
        return await self.scatter_from_king(vals, sid, timeout=timeout)

    async def flush_telemetry(self) -> None:
        """Round-boundary telemetry flush (docs/OBSERVABILITY.md). The
        default is a no-op: in-process backends share one span buffer, so
        the LocalSimNet round harness merges by pid at the round's end
        (`aggregate.merge_local`); ProdNet overrides this to ship a
        TELEMETRY frame across the real transport."""
        return None


class LocalSimNet(BaseNet):
    """In-process n-party network: one shared mailbox fabric, one instance
    per party. The LocalTestNet role (multi.rs:227-316) without sockets."""

    def __init__(
        self, party_id: int, n_parties: int, fabric,
        net_cfg: NetConfig | None = None,
    ):
        self.party_id = party_id
        self.n_parties = n_parties
        self._fabric = fabric
        self.net_cfg = net_cfg

    async def _send_impl(self, to: int, value: Any, sid: int) -> None:
        if not (0 <= to < self.n_parties) or to == self.party_id:
            raise MpcNetError(f"bad destination {to}",
                              party=self.party_id, peer=to, sid=sid)
        await self._fabric[(self.party_id, to, sid)].put(value)

    async def _recv_impl(self, frm: int, sid: int) -> Any:
        if not (0 <= frm < self.n_parties) or frm == self.party_id:
            raise MpcNetError(f"bad source {frm}",
                              party=self.party_id, peer=frm, sid=sid)
        return await self._fabric[(frm, self.party_id, sid)].get()


def make_local_nets(
    n_parties: int, net_cfg: NetConfig | None = None
) -> list[LocalSimNet]:
    """One LocalSimNet per party over a fresh shared fabric."""
    fabric = {
        (s, d, c): asyncio.Queue()
        for s in range(n_parties)
        for d in range(n_parties)
        for c in range(CHANNELS)
        if s != d
    }
    return [
        LocalSimNet(i, n_parties, fabric, net_cfg) for i in range(n_parties)
    ]


def simulate_network_round(
    n_parties: int,
    closure: Callable[[Net, Any], Awaitable[Any]],
    per_party_data: Sequence[Any] | None = None,
    net_cfg: NetConfig | None = None,
) -> list:
    """Run `closure(net, data)` concurrently for every party; return results
    ordered by party id (mpc-net/src/multi.rs:289-316 harness)."""

    async def _run():
        nets = make_local_nets(n_parties, net_cfg)
        tasks = [
            closure(
                nets[i],
                per_party_data[i] if per_party_data is not None else None,
            )
            for i in range(n_parties)
        ]
        out = await asyncio.gather(*tasks)
        # the round boundary of the in-process star: every party's spans
        # are in the shared aggregation buffer — merge them by pid and
        # close the round (critical-path series) while they're complete
        if _agg.enabled():
            _agg.merge_local(finish=True)
        return out

    return asyncio.run(_run())


def run_round_with_retries(
    n_parties: int,
    closure: Callable[[Net, Any], Awaitable[Any]],
    per_party_data: Sequence[Any] | None = None,
    *,
    retries: int = 2,
    net_cfg: NetConfig | None = None,
    on_retry: Callable[[int, MpcNetError], None] | None = None,
) -> list:
    """`simulate_network_round` with bounded re-runs on transport faults.

    A transient transport fault (MpcTimeoutError / MpcDisconnectError)
    re-runs the WHOLE round on a fresh fabric — the retryable-round
    contract the multi-hour provers need: a flaky link costs one round,
    not the proof. Application-level exceptions — including plain
    MpcNetError protocol misuse (bad destination, wrong scatter length),
    which is deterministic and would fail identically on every re-run —
    propagate immediately; after `retries` re-runs the last transient
    error propagates too.
    """
    attempts = retries + 1
    for attempt in range(attempts):
        try:
            return simulate_network_round(
                n_parties, closure, per_party_data, net_cfg
            )
        except (MpcTimeoutError, MpcDisconnectError) as e:
            if attempt == attempts - 1:
                _ROUND_FAILURES.inc()
                # retry exhaustion is a fault trigger: leave a post-mortem
                # with the last rounds' spans and net events
                _flight.dump(
                    "round_retry_exhausted",
                    extra={"attempts": attempts, "error": str(e)},
                )
                raise
            _ROUND_RETRIES.inc()
            _flight.note("round_retry", attempt=attempt, error=str(e))
            # the failed attempt never reached its round-boundary merge —
            # drop its spans so the NEXT attempt's critical path doesn't
            # span both attempts plus the backoff gap (the flight
            # recorder's ring keeps its own copy for the post-mortem)
            if _agg.enabled():
                _agg.drain()
            log.warning(
                "round attempt %d/%d failed (%s); retrying",
                attempt + 1, attempts, e,
            )
            if on_retry is not None:
                on_retry(attempt, e)
    raise AssertionError("unreachable")
