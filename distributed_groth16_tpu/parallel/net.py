"""Transport-agnostic star-topology collectives ("the NCCL layer").

Re-imagines the reference's mpc-net crate (mpc-net/src/lib.rs:37-155) for the
TPU build. The collective vocabulary is exactly the reference's three
primitives plus point-to-point sends:

  * gather_to_king    — client_send_or_king_receive (lib.rs:61-99): every
                        party contributes one value; the king gets the full
                        list ordered by party id (own value included), clients
                        get None.
  * scatter_from_king — client_receive_or_king_send (lib.rs:102-139): king
                        provides one value per party (keeps its own), clients
                        receive theirs.
  * king_compute      — fused gather -> f on king -> scatter (lib.rs:146-155).

Three logical channels (CHANNELS = 3, mirroring MultiplexedStreamID::
{Zero,One,Two}, lib.rs:28-33) let three independent collectives overlap —
the a/b/c FFT pipelines and the W/U/H MSMs of the prover.

Unlike the reference, values are arbitrary Python objects (typically JAX
arrays or pytrees of them): the typed-serialization layer (dist-primitives'
MpcSerNet) is only needed at a real process boundary and lives with the
gRPC/TLS transport; in-process backends hand device buffers over directly —
zero-copy, no host round-trip.

Backends:
  * LocalSimNet — n asyncio tasks + in-memory queues, the LocalTestNet /
    ChannelIO analog (mpc-net/src/multi.rs:227, prod.rs:409-491) used by all
    distributed tests. Harness: `simulate_network_round` (multi.rs:289-316).
  * planned: a sharded single-program mesh backend (parties = mesh shards,
    collectives = XLA all_gather/ppermute over ICI) and a TLS star over DCN
    for true multi-host MPC.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Protocol, Sequence

# module-level tracing, the role of the reference's log/env_logger calls
# throughout mpc-net (multi.rs:149,:182); enable with
# logging.getLogger("distributed_groth16_tpu").setLevel(logging.DEBUG)
log = logging.getLogger(__name__)

CHANNELS = 3


class MpcNetError(RuntimeError):
    pass


class Net(Protocol):
    """The MpcNet-shaped async interface every distributed kernel takes."""

    party_id: int
    n_parties: int

    @property
    def is_king(self) -> bool: ...

    async def send_to(self, to: int, value: Any, sid: int = 0) -> None: ...

    async def recv_from(self, frm: int, sid: int = 0) -> Any: ...

    async def gather_to_king(self, value: Any, sid: int = 0): ...

    async def scatter_from_king(self, values, sid: int = 0): ...


class BaseNet:
    """Collectives implemented over send_to/recv_from (as in the reference,
    where they are trait default methods)."""

    party_id: int
    n_parties: int

    @property
    def is_king(self) -> bool:
        return self.party_id == 0

    async def send_to(self, to: int, value: Any, sid: int = 0) -> None:
        raise NotImplementedError

    async def recv_from(self, frm: int, sid: int = 0) -> Any:
        raise NotImplementedError

    async def gather_to_king(self, value: Any, sid: int = 0):
        """King returns [v_0, ..., v_{n-1}] (own value at index 0);
        clients send and return None."""
        if self.is_king:
            log.debug("gather_to_king: king collecting %d values (sid=%d)",
                      self.n_parties, sid)
            out = [value]
            recvs = [
                self.recv_from(i, sid) for i in range(1, self.n_parties)
            ]
            out.extend(await asyncio.gather(*recvs))
            return out
        log.debug("gather_to_king: party %d sending (sid=%d)",
                  self.party_id, sid)
        await self.send_to(0, value, sid)
        return None

    async def scatter_from_king(self, values, sid: int = 0):
        """King passes one value per party (or None if client); every party
        returns its own value."""
        if self.is_king:
            if values is None:
                raise MpcNetError("scatter_from_king: king must provide values")
            if len(values) != self.n_parties:
                raise MpcNetError(
                    f"scatter_from_king: {len(values)} values for "
                    f"{self.n_parties} parties"
                )
            log.debug("scatter_from_king: king fanning out %d values "
                      "(sid=%d)", len(values), sid)
            sends = [
                self.send_to(i, values[i], sid)
                for i in range(1, self.n_parties)
            ]
            await asyncio.gather(*sends)
            return values[0]
        if values is not None:
            raise MpcNetError("scatter_from_king: client must pass None")
        return await self.recv_from(0, sid)

    async def king_compute(
        self,
        value: Any,
        f: Callable[[list], list],
        sid: int = 0,
    ):
        """gather -> f on king -> scatter (MpcNet::king_compute)."""
        gathered = await self.gather_to_king(value, sid)
        out = f(gathered) if gathered is not None else None
        return await self.scatter_from_king(out, sid)

    async def broadcast_from_king(self, value: Any, sid: int = 0):
        """King's value to everyone (the d_msm result fan-out,
        dmsm/mod.rs:94-97)."""
        vals = [value] * self.n_parties if self.is_king else None
        return await self.scatter_from_king(vals, sid)


class LocalSimNet(BaseNet):
    """In-process n-party network: one shared mailbox fabric, one instance
    per party. The LocalTestNet role (multi.rs:227-316) without sockets."""

    def __init__(self, party_id: int, n_parties: int, fabric):
        self.party_id = party_id
        self.n_parties = n_parties
        self._fabric = fabric

    async def send_to(self, to: int, value: Any, sid: int = 0) -> None:
        if not (0 <= to < self.n_parties) or to == self.party_id:
            raise MpcNetError(f"bad destination {to}")
        await self._fabric[(self.party_id, to, sid)].put(value)

    async def recv_from(self, frm: int, sid: int = 0) -> Any:
        if not (0 <= frm < self.n_parties) or frm == self.party_id:
            raise MpcNetError(f"bad source {frm}")
        return await self._fabric[(frm, self.party_id, sid)].get()


def make_local_nets(n_parties: int) -> list[LocalSimNet]:
    """One LocalSimNet per party over a fresh shared fabric."""
    fabric = {
        (s, d, c): asyncio.Queue()
        for s in range(n_parties)
        for d in range(n_parties)
        for c in range(CHANNELS)
        if s != d
    }
    return [LocalSimNet(i, n_parties, fabric) for i in range(n_parties)]


def simulate_network_round(
    n_parties: int,
    closure: Callable[[Net, Any], Awaitable[Any]],
    per_party_data: Sequence[Any] | None = None,
) -> list:
    """Run `closure(net, data)` concurrently for every party; return results
    ordered by party id (mpc-net/src/multi.rs:289-316 harness)."""

    async def _run():
        nets = make_local_nets(n_parties)
        tasks = [
            closure(
                nets[i],
                per_party_data[i] if per_party_data is not None else None,
            )
            for i in range(n_parties)
        ]
        return await asyncio.gather(*tasks)

    return asyncio.run(_run())
