"""Distributed MSM over packed shares — hot kernel #2.

d_msm (dist-primitives/src/dmsm/mod.rs:70-98): every party runs one local
Pippenger MSM over its m/l packed-share (bases, scalars) — the dominant
compute, on-device via ops/msm.py — producing one group element whose
sharing polynomial has degree 2(t+l). The king gathers the n points,
unpacks them in the exponent (degree2), sums the l recovered partial MSMs
and broadcasts the final value.

Communication: O(1) group elements per party — d_msm is compute-bound.
"""

from __future__ import annotations

import logging

from ..ops.curve import CurvePoints
from ..ops.field import fr
from ..ops.msm import msm
from ..telemetry import tracing as _tracing
from .net import Net
from .pss import PackedSharingParams

log = logging.getLogger(__name__)


async def d_msm(
    curve: CurvePoints,
    bases,
    scalar_shares,
    pp: PackedSharingParams,
    net: Net,
    sid: int = 0,
    scalar_field=None,
):
    """bases: (c, 3) + elem packed-in-the-exponent CRS shares;
    scalar_shares: (c, 16) Montgomery-form packed witness shares.
    Returns the clear MSM result (3,) + elem on every party.

    scalar_field: the PrimeField the shares live in — defaults to BN254
    Fr; pass ops.bls12_377.fr377() (with pp = bls12_377.pss377(l)) for the
    reference's BLS12-377 configuration (dmsm_bench.rs:42-50; d_msm itself
    is curve-generic there, dmsm/mod.rs:70)."""
    F = scalar_field or fr()
    log.debug("d_msm: party %d local MSM over %d bases (sid=%d)",
              net.party_id, bases.shape[0], sid)
    with _tracing.span("dmsm", party=net.party_id, sid=sid):
        # wide standard forms (r381 -> 17 limbs) pass through unchanged:
        # ops/msm.py's digit decomposition is width-aware as of r5
        std = F.from_mont(scalar_shares)
        local = msm(curve, bases, std)

        def king(points):
            import jax.numpy as jnp

            stacked = jnp.stack(points, axis=0)  # (n, 3) + elem
            partials = pp.unpackexp(curve, stacked, degree2=True)  # (l, 3)+
            total = curve.sum(partials, axis=0)
            return [total] * pp.n

        return await net.king_compute(local, king, sid)
