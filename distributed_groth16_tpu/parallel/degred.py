"""King-mediated degree reduction (dist-primitives/src/utils/deg_red.rs:10-28):
gather degree-2(t+l) shares, unpack2 + re-pack every chunk (one batched
tiny-NTT kernel on the king), scatter fresh degree-(t+l) shares."""

from __future__ import annotations

import jax.numpy as jnp

from .net import Net
from .pss import PackedSharingParams


async def deg_red(px, pp: PackedSharingParams, net: Net, sid: int = 0):
    """px: (c, 16) per-party share vector -> (c, 16) reduced-degree shares."""

    def king(vals):
        x = jnp.swapaxes(jnp.stack(vals, axis=0), 0, 1)  # (c, n, 16)
        out = pp.pack_from_public(pp.unpack2(x))  # (c, n, 16)
        per_party = jnp.swapaxes(out, 0, 1)
        return [per_party[i] for i in range(pp.n)]

    return await net.king_compute(px, king, sid)
