"""Bounded worker pool + the proof executor it drives.

The pool is DG16_SERVICE_WORKERS asyncio tasks pulling from the JobQueue;
each job's body runs in a thread (`asyncio.to_thread`) because proving is
synchronous JAX compute and the in-process MPC round owns its own event
loop (`simulate_network_round` calls `asyncio.run`). At most `workers`
proofs execute concurrently — the admission bound on the queue plus this
pool is the whole backpressure story.

`ProofExecutor` is the single proving path of the service: witness
generation, CRS packing (through the packed-CRS cache), and the MPC round
via PR 1's `run_round_with_retries` so a transient transport fault costs
one round, not the job. Cooperative cancellation points sit between
phases (`job.check_cancel()`).
"""

from __future__ import annotations

import asyncio
import logging

from ..frontend.ark_serde import proof_to_bytes
from ..frontend.readers import read_wtns
from ..models.groth16 import (
    CompiledR1CS,
    distributed_prove_party,
    pack_from_witness,
    pack_proving_key,
    reassemble_proof,
)
from ..models.groth16.prove import prove_single
from ..ops.field import fr
from ..parallel.net import job_context, run_round_with_retries
from ..parallel.pss import PackedSharingParams
from ..telemetry import aggregate, devmem, logbus, tracing, transfer
from ..utils.config import ServiceConfig
from ..utils.timers import phase
from ..verifier.executor import VerifyExecutor
from .crs_cache import CrsCache
from .jobs import JobCancelled, JobState, ProofJob
from .queue import JobQueue

log = logging.getLogger(__name__)


class ProofExecutor:
    """Runs one ProofJob to a result dict — always on a worker thread."""

    def __init__(
        self,
        store,
        crs_cache: CrsCache | None = None,
        cfg: ServiceConfig | None = None,
    ):
        self.store = store
        self.cfg = cfg or ServiceConfig()
        # explicit None check: an EMPTY CrsCache is falsy (it has __len__),
        # so `crs_cache or ...` would silently split the server's cache
        # from the executor's
        self.crs_cache = (
            crs_cache
            if crs_cache is not None
            else CrsCache(self.cfg.crs_cache_size)
        )
        # the verification plane's executor (verifier/executor.py): owns
        # the PreparedVerifyingKey cache the same way this executor owns
        # the packed-CRS cache, sized by the same knob
        self.verifier = VerifyExecutor(store)
        self.verifier.pvk_cache.capacity = self.cfg.crs_cache_size

    # -- witness -------------------------------------------------------------

    def resolve_witness(self, job: ProofJob, r1cs) -> list[int]:
        """Resolve + validate a job's witness assignment. Public because
        the batching scheduler's BatchProver resolves each batched job's
        witness through the same path (scheduler/batch_prover.py)."""
        fields = job.fields
        if "witness_file" in fields:
            z = read_wtns(fields["witness_file"])
        elif "input_file" in fields:
            # the reference's primary prove flow (mpc-api/src/main.rs:
            # 282-421): JSON inputs -> circom WASM witness generation on
            # the pure-Python interpreter (frontend/wasm_vm.py)
            import json

            from ..frontend.witness_calculator import WitnessCalculator

            _, wasm = self.store.get_files(job.circuit_id)
            if not wasm:
                raise ValueError(
                    "circuit was saved without a witness_generator wasm; "
                    "upload a .wtns in the witness_file field instead"
                )
            inputs = json.loads(fields["input_file"].decode())
            wc = WitnessCalculator(wasm)
            z = wc.calculate_witness(inputs)
        else:
            raise ValueError("need witness_file or input_file")
        if len(z) != r1cs.num_wires or not r1cs.is_satisfied(z):
            raise ValueError("witness does not satisfy the circuit")
        return z

    # -- CRS -----------------------------------------------------------------

    def packed_crs(self, job: ProofJob, pk, pp: PackedSharingParams):
        """All-party CRS shares through the LRU cache. The key is the
        circuit plus every parameter the shares depend on (l determines
        n/t and the chunking). A cache MISS is the packed-CRS
        host->device boundary: the factory accounts the share bytes it
        materialized on device (hits move nothing, and count nothing)."""

        def _pack():
            with transfer.account("h2d") as t:
                shares = pack_proving_key(pk, pp, strip=True)
                # PackedProvingKeyShare is a plain dataclass, not a
                # registered pytree — count its array fields explicitly
                t.add_tree([tuple(vars(sh).values()) for sh in shares])
            return shares

        key = (job.circuit_id, pp.l)
        return self.crs_cache.get_or_pack(key, _pack)

    # -- the proving path ----------------------------------------------------

    def run(self, job: ProofJob) -> dict:
        """Executor entry: every span below lands in the job's own trace
        buffer (GET /jobs/{id} metrics block — and DG16_TRACE_OUT, if
        set), and any transport failure inside the MPC round carries the
        job id (net.job_context -> MpcNetError.job_id)."""
        attrs = {"kind": job.kind, "circuit": job.circuit_id}
        if job.trace_id:
            # the cross-tier trace context (docs/OBSERVABILITY.md "Fleet
            # observatory"): every span nested under the job root joins
            # the router-minted trace via this attribute
            attrs["trace"] = job.trace_id
        # bracket the job with the device-memory peak so the DTO can say
        # how much IT raised the process HBM high-water mark (None on
        # XLA:CPU — devmem is None-safe end to end)
        peak0 = devmem.peak_bytes()
        try:
            with tracing.collect(job.trace), job_context(job.id), tracing.span(
                "job", job=job.id, attrs=attrs,
            ), logbus.bind(tenant=job.tenant, priority=job.priority):
                try:
                    return self._run(job)
                except JobCancelled:
                    raise
                except Exception as e:  # noqa: BLE001 — logged, re-raised
                    # log the failure INSIDE the job's trace/log context:
                    # the record lands in the ring carrying this job's
                    # trace id, and its WARN+ instant event lands in the
                    # job's own Chrome trace at the fault instant
                    log.error("job %s failed: %s", job.id, e)
                    raise
        finally:
            job.note_device_memory(
                devmem.peak_delta(peak0, devmem.peak_bytes())
            )

    def _run(self, job: ProofJob) -> dict:
        if job.kind in ("verify", "aggregate"):
            # verification plane (docs/VERIFY.md): same tracing/cancel
            # envelope, entirely different body — no witness, no CRS,
            # no mesh
            return self.verifier.run_job(job)
        timings = job.timings
        job.note_phase("load")
        with phase("load", timings):
            r1cs, pk = self.store.load(job.circuit_id)
        job.check_cancel()
        job.note_phase("witness")
        with phase("witness", timings):
            z = self.resolve_witness(job, r1cs)
        job.check_cancel()
        F = fr()
        # the witness-upload boundary: F.encode materializes the (wires,
        # 16) Montgomery limb tensor on device from host bigints
        with transfer.account("h2d") as t:
            z_mont = F.encode(z)
            t.add_tree(z_mont)
        if job.kind == "prove":
            job.note_phase("prove")
            with phase("prove", timings):
                comp = CompiledR1CS(r1cs)
                proof = prove_single(pk, comp, z_mont)
        elif job.kind == "mpc_prove":
            pp = PackedSharingParams(job.l)
            job.note_phase("packing")
            with phase("packing", timings):
                comp = CompiledR1CS(r1cs)
                qap_shares = comp.qap(z_mont).pss(pp)
                crs_shares = self.packed_crs(job, pk, pp)
                ni = r1cs.num_instance
                a_sh = pack_from_witness(pp, z_mont[1:])
                ax_sh = pack_from_witness(pp, z_mont[ni:])
            job.check_cancel()

            async def party(net, d):
                return await distributed_prove_party(
                    pp, d[0], d[1], d[2], d[3], net
                )

            # round boundary for the aggregation plane: the load/witness/
            # packing spans above are harness (pid 0) work — drop them so
            # the round close at simulate_network_round's end decomposes
            # only the MPC round (million.py does the same; concurrent
            # jobs on one process buffer still interleave — the per-job
            # windowed decomposition in jobs.py is the exact one)
            if aggregate.enabled():
                aggregate.drain()

            job.note_phase("MPC Proof")
            with phase("MPC Proof", timings):
                res = run_round_with_retries(
                    pp.n,
                    party,
                    [
                        (crs_shares[i], qap_shares[i], a_sh[i], ax_sh[i])
                        for i in range(pp.n)
                    ],
                    retries=self.cfg.round_retries,
                )
            proof = reassemble_proof(res[0], pk)
        else:
            raise ValueError(f"unknown job kind {job.kind!r}")
        job.note_phase(None)
        job.check_cancel()
        # the proof-readback boundary: serializing pulls the proof's
        # device-resident curve points back to host
        with transfer.account("d2h") as t:
            proof_bytes = proof_to_bytes(proof)
            t.add(len(proof_bytes))
        return {
            "circuitId": job.circuit_id,
            "proof": list(proof_bytes),
            "phases": timings.as_millis(),
        }


class WorkerPool:
    """DG16_SERVICE_WORKERS asyncio tasks draining the JobQueue.

    With a batching scheduler attached (DG16_BATCH_MAX > 1 —
    scheduler/BatchScheduler, docs/SCHEDULER.md) the workers become
    FEEDERS for batch-eligible jobs: popped jobs are offered to the
    bucketer and the scheduler runs released batches end-to-end under
    mesh leases, so proving concurrency is bounded by mesh slices rather
    than worker count. Ineligible jobs (and every job when the scheduler
    is absent) take the per-job executor path below, unchanged."""

    def __init__(self, queue: JobQueue, executor: ProofExecutor,
                 workers: int = 2, scheduler=None):
        self.queue = queue
        self.executor = executor
        self.workers = max(1, workers)
        self.scheduler = scheduler
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        if self.scheduler is not None:
            await self.scheduler.start()
        for i in range(self.workers):
            self._tasks.append(
                asyncio.create_task(self._worker(i), name=f"dg16-worker-{i}")
            )

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self.scheduler is not None:
            # flushes still-lingering bucketed jobs to a terminal state
            # and waits out in-flight batches (their proofs are results)
            await self.scheduler.stop()
        # jobs still QUEUED will never get a worker now — transition them
        # so sync waiters and status pollers see a terminal state instead
        # of QUEUED forever (and of stalling graceful shutdown).
        # fail_terminal journals the failure BEFORE the in-memory
        # transition so a crash mid-shutdown can't resurrect them.
        for job in self.queue.drain_pending():
            self.queue.fail_terminal(job, RuntimeError("service shutting down"))

    async def _worker(self, idx: int) -> None:
        while True:
            job = await self.queue.get()
            if job.state is not JobState.QUEUED:
                continue  # cancelled while queued — never runs
            if self.scheduler is not None and self.scheduler.eligible(job):
                # feed the bucketer; `offer` blocks when the scheduler is
                # saturated (backpressure: the queue refills and 429s
                # keep firing at the admission bound)
                await self.scheduler.offer(job)
                continue
            job.mark_running()
            self.queue.on_started(job)
            fut = asyncio.ensure_future(
                asyncio.to_thread(self.executor.run, job)
            )
            try:
                result = await asyncio.shield(fut)
            except asyncio.CancelledError:
                # pool shutdown. The proof thread can't be interrupted, so
                # ask for a phase-boundary stop, wait it out, and record
                # the real outcome — a proof that finished during shutdown
                # is a result, not a failure.
                job.request_cancel()
                try:
                    result = await fut
                except JobCancelled:
                    job.mark_cancelled()
                except Exception as e:  # noqa: BLE001
                    job.mark_failed(e)
                else:
                    job.mark_done(result)
                self.queue.on_finished(job)
                raise
            except JobCancelled:
                job.mark_cancelled()
            except Exception as e:  # noqa: BLE001 — job-level CustomError
                # the loop thread runs outside the job's trace context —
                # correlate explicitly via the structured-extras API
                log.warning(
                    "job %s failed: %s", job.id, e,
                    extra={"job": job.id, "trace": job.trace_id},
                )
                job.mark_failed(e)
            else:
                job.mark_done(result)
            self.queue.on_finished(job)
