"""Proof-job records: the unit of work the service layer schedules.

A `ProofJob` is everything the worker pool needs to run one proof off the
request path — the parsed submission payload, lifecycle state, wall-clock
stamps, per-phase timings, and (on completion) either a result payload or
a structured error. State machine:

    QUEUED --> RUNNING --> DONE
       |          |`-----> FAILED
       |          `------> CANCELLED   (cooperative, between phases)
       `-----------------> CANCELLED   (never ran)

All state transitions happen on the event-loop thread (the worker pool's
tasks); the executor thread only reads `cancel_requested` (a
threading.Event) and writes through the transition helpers' return values,
so no per-job lock is needed.
"""

from __future__ import annotations

import asyncio
import enum
import json
import re
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from ..telemetry import aggregate as _aggregate
from ..telemetry import logbus as _logbus
from ..telemetry.tracing import TraceBuffer, chrome_envelope
from ..utils.timers import PhaseTimings

# Failure-DTO sanitization (docs/ROBUSTNESS.md): exception messages are
# operator-facing via GET /jobs/{id} AND durable via the job journal, so
# they must not leak witness-adjacent material. Two redactions cover the
# real leak vectors observed in practice: filesystem paths (a failed
# witness upload names the tmp file it was spooled to) and huge integer
# literals (a field-element mismatch embeds the ~77-digit value).
_PATH_RE = re.compile(r"(?:/[\w.+-]+){2,}/?")
_BIGINT_RE = re.compile(r"\d{20,}")
_MESSAGE_CAP = 300


# how many of the job's own log records the status DTO carries — a tail,
# not the firehose (the full filtered stream lives behind GET /logs)
LOG_TAIL = 50


def sanitize_message(msg: str) -> str:
    msg = _PATH_RE.sub("<path>", msg)
    msg = _BIGINT_RE.sub("<bigint>", msg)
    if len(msg) > _MESSAGE_CAP:
        msg = msg[:_MESSAGE_CAP] + "…"
    return msg


def error_dto(exc: BaseException, phase: str | None = None) -> dict[str, Any]:
    """The structured failure shape every surface shares — status DTO,
    journal record, shutdown pre-journal: {type, message, phase}, never
    a raw repr(exc)."""
    return {
        "type": type(exc).__name__,
        "message": sanitize_message(str(exc)),
        "phase": phase,
    }


class JobState(str, enum.Enum):
    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    DONE = "DONE"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    @property
    def terminal(self) -> bool:
        return self in (JobState.DONE, JobState.FAILED, JobState.CANCELLED)


class JobCancelled(Exception):
    """Raised by the executor at a cooperative cancellation point."""


@dataclass
class ProofJob:
    """One queued proving request.

    kind:    "prove" (single-prover) | "mpc_prove" (packed-MPC round) |
             "verify" (batched Groth16 verification, docs/VERIFY.md) |
             "aggregate" (RLC proof-bundle attestation)
    fields:  the raw multipart fields of the submission (witness bytes,
             JSON inputs, or a proofs_file batch) — parsed lazily by the
             executor, off the request path.
    """

    kind: str
    circuit_id: str
    fields: dict[str, bytes]
    l: int = 2
    # fleet identity (docs/FLEET.md): which tenant submitted the job
    # (X-DG16-Tenant at the router/replica door) and its priority class.
    # Pure metadata at the replica — quotas and weighted-fair dispatch
    # are enforced at the router; here they ride the DTO and the journal
    # so a handoff re-routes under the right tenant.
    tenant: str = ""
    priority: str = ""
    # end-to-end trace context (docs/OBSERVABILITY.md "Fleet
    # observatory"): minted by the fleet router next to the idempotent
    # job id and propagated via the X-DG16-Trace header, or minted at
    # the replica door for direct submissions. Rides the DTO and the
    # journal so a handoff re-proves under the SAME trace, and the
    # stitched fleet trace can join router spans to replica spans.
    trace_id: str = ""
    id: str = field(default_factory=lambda: uuid.uuid4().hex)
    state: JobState = JobState.QUEUED
    created_at: float = field(default_factory=time.time)
    started_at: float | None = None
    finished_at: float | None = None
    timings: PhaseTimings = field(default_factory=PhaseTimings)
    # per-proof span trace (telemetry/tracing.py): the executor collects
    # into this while the job runs; GET /jobs/{id} returns it as a span
    # tree. Bounded so 1024 retained terminal jobs stay cheap.
    trace: TraceBuffer = field(
        default_factory=lambda: TraceBuffer(max_events=4096),
        repr=False, compare=False,
    )
    result: dict[str, Any] | None = None
    error: dict[str, Any] | None = None

    def __post_init__(self):
        import threading

        # set from the event loop on DELETE, read from the executor thread
        # at phase boundaries — the only cross-thread signal a job carries
        self._cancel_flag = threading.Event()
        self._done = asyncio.Event()
        # terminal-state trace snapshots (see _finish): the span tree for
        # the status DTO, the raw Chrome trace for GET /jobs/{id}/trace,
        # and the round critical-path decomposition
        self._spans_json: str | None = None
        self._chrome_json: str | None = None
        self._logs_json: str | None = None
        self._critical_path: dict | None = None
        self._dropped_spans = 0
        # the phase the executor is currently in (note_phase) — failure
        # DTOs carry it so "FAILED" says where; written from the worker
        # thread, read at the loop-side terminal transition (a str swap
        # is atomic, no lock needed)
        self._phase: str | None = None
        # device-memory stamp (telemetry/devmem.py): how much this job
        # raised the process HBM peak — written by the executor / batch
        # prover thread, None on backends without memory_stats (XLA:CPU)
        self._device_memory: dict | None = None

    # -- executor-side hooks (worker thread) --------------------------------

    def check_cancel(self) -> None:
        """Cooperative cancellation point; the executor calls this between
        phases so a cancel costs at most one phase, not the whole proof."""
        if self._cancel_flag.is_set():
            raise JobCancelled(self.id)

    def note_phase(self, name: str | None) -> None:
        """Executors stamp the phase they are entering so a failure DTO
        can say WHERE the job died ({type, message, phase})."""
        self._phase = name

    def note_device_memory(self, doc: dict | None) -> None:
        """Stamp the job's device-memory footprint ({peakBytes,
        peakDeltaBytes}, plus batchSize on the batched path) into the
        status DTO — None-safe where the backend reports nothing."""
        if doc is not None:
            self._device_memory = doc

    # -- loop-side transitions ----------------------------------------------

    def mark_running(self) -> None:
        self.state = JobState.RUNNING
        self.started_at = time.time()

    def mark_done(self, result: dict[str, Any]) -> None:
        self.state = JobState.DONE
        self.result = result
        self._finish()

    def mark_failed(self, exc: BaseException) -> None:
        self.state = JobState.FAILED
        self.error = error_dto(exc, phase=self._phase)
        self._finish()

    def mark_cancelled(self) -> None:
        self.state = JobState.CANCELLED
        self._finish()

    def request_cancel(self) -> None:
        self._cancel_flag.set()

    @property
    def cancel_requested(self) -> bool:
        """True once DELETE landed — read by the batching scheduler at
        batch-admission time (a job cancelled while lingering in a bucket
        must never enter a batch) and by the executor's check_cancel."""
        return self._cancel_flag.is_set()

    @property
    def bucket(self) -> str:
        """Coarse bucket label — the runtime-EMA key (service/queue.py).
        Cheap on purpose (no store lookup): kind + circuit + packing
        factor determine the work shape closely enough for retryAfter
        estimation; the scheduler's full BucketKey adds the shape fields
        it must not guess."""
        return f"{self.kind}:{self.circuit_id}:l{self.l}"

    def _finish(self) -> None:
        self.finished_at = time.time()
        # the submission payload (witness bytes, up to the 100 MB body cap)
        # is dead weight once the job is terminal — drop it so retained
        # terminal jobs cost registry metadata, not upload-sized buffers
        self.fields = {}
        # likewise the raw trace events: up to 4096 dicts per job across
        # 1024 retained jobs is hundreds of MB of Python objects. Compact
        # the span tree + the Chrome trace to JSON strings (tens of KB)
        # and drop them. An MPC job's trace holds EVERY party's spans
        # (the contextvar buffer flows into the per-party tasks), so the
        # Chrome export is already the merged per-job timeline — one
        # track per party — and supports a critical-path decomposition.
        self._dropped_spans = self.trace.dropped
        # snapshot this job's slice of the structured log ring NOW — the
        # shared ring keeps rolling after the job is terminal, and the
        # status DTO must keep answering "what did this job log" after
        # its records fell off (telemetry/logbus.py)
        self._logs_json = json.dumps(
            _logbus.ring().query(job=self.id, limit=LOG_TAIL)
        )
        events = self.trace.events()
        self._spans_json = json.dumps(self.trace.span_tree())
        self._chrome_json = json.dumps(self._envelope(events))
        if events:
            # window the decomposition to the MPC round: the harness
            # spans ("job", the load/witness/packing phases) are pid-0
            # wrappers covering the whole timeline, which would read as
            # king ~= wall and wire ~= 0. Inside the "MPC Proof" phase
            # the only spans are the per-party rounds, so the
            # king/straggler/wire split is real. A non-MPC job keeps the
            # whole-trace numbers (single-track: never recorded anyway).
            window = [e for e in events if e.get("name") == "MPC Proof"]
            if window:
                w0 = window[0]["ts"]
                w1 = w0 + window[0]["dur"]
                round_evs = [
                    e for e in events
                    if e.get("name") != "MPC Proof"
                    and e.get("ts", 0) >= w0
                    and e.get("ts", 0) + e.get("dur", 0) <= w1
                ]
            else:
                round_evs = events
            cp = _aggregate.critical_path(round_evs)
            self._critical_path = cp
            # record into the shared round series only when the plane is
            # OFF — with DG16_AGG on, the round boundary (merge_local's
            # finish_round) already recorded this round, and recording
            # here too would double every histogram sample. Single-track
            # jobs have no straggler and are never recorded.
            if cp["parties"] > 1 and not _aggregate.enabled():
                _aggregate.record_critical_path(cp)
        self.trace.clear()
        self._done.set()

    async def wait(self) -> "ProofJob":
        """Block until the job reaches a terminal state (the sync API
        wrappers' submit-and-await path)."""
        await self._done.wait()
        return self

    def _envelope(self, events: list) -> dict:
        """The job's Chrome trace object, stamped with the trace id so a
        downloaded file still says which end-to-end trace it belongs to
        (viewers ignore the extra key)."""
        env = chrome_envelope(events)
        if self.trace_id:
            env["traceId"] = self.trace_id
        return env

    def chrome_trace_json(self) -> str:
        """The job's Chrome trace-event JSON (GET /jobs/{id}/trace):
        the compacted snapshot once terminal, the live buffer before."""
        if self._chrome_json is not None:
            return self._chrome_json
        return json.dumps(self._envelope(self.trace.events()))

    @property
    def runtime_s(self) -> float | None:
        if self.started_at is None or self.finished_at is None:
            return None
        return self.finished_at - self.started_at

    def to_dict(self) -> dict[str, Any]:
        """The GET /jobs/{id} status DTO."""
        out = {
            "jobId": self.id,
            "kind": self.kind,
            "circuitId": self.circuit_id,
            "tenant": self.tenant,
            "priority": self.priority,
            "traceId": self.trace_id,
            "state": self.state.value,
            "createdAt": self.created_at,
            "startedAt": self.started_at,
            "finishedAt": self.finished_at,
            "phases": self.timings.as_millis(),
            "metrics": (
                {
                    "spans": json.loads(self._spans_json),
                    "droppedSpans": self._dropped_spans,
                    "criticalPath": self._critical_path,
                    "deviceMemory": self._device_memory,
                }
                if self._spans_json is not None
                else {
                    "spans": self.trace.span_tree(),
                    "droppedSpans": self.trace.dropped,
                    "criticalPath": None,
                    "deviceMemory": self._device_memory,
                }
            ),
        }
        # the job's correlated log tail (docs/OBSERVABILITY.md "Logging
        # spine"): terminal jobs serve the _finish snapshot, running jobs
        # a live ring query keyed on the job id
        out["logs"] = (
            json.loads(self._logs_json)
            if self._logs_json is not None
            else _logbus.ring().query(job=self.id, limit=LOG_TAIL)
        )
        if self.error is not None:
            out["error"] = self.error
        return out
