"""SLO burn-rate monitor: error budgets derived from existing series.

The service already measures everything an SLO needs — `job_seconds{kind}`
histograms and the `jobs_*_total` counters (PR 3) — but a router or
autoscaler can't act on raw histograms: it needs ONE number per replica
per job kind saying "this replica is eating its error budget N times
faster than sustainable". That is the burn rate.

Definitions (Google SRE workbook semantics, latency SLO):

  * A job of kind k is GOOD when its end-to-end runtime lands within the
    kind's target (`SLOConfig.target_for`), BAD otherwise. Goodness is
    read off the `job_seconds{kind}` bucket counts — observations in
    buckets whose upper bound <= target count as good, so a target
    between bucket bounds is rounded DOWN (conservative: jobs in the
    straddling bucket count bad). Failed jobs observe their runtime too,
    so a fast-failing job only burns budget via `jobs_finished_total`
    dashboards — the SLO here is a latency objective.
  * Error budget: over a rolling `window_s`, `(1 - objective)` of the
    kind's jobs may be bad.
  * `slo_burn_rate{kind}` = (bad/total in window) / (1 - objective) —
    1.0 means "exactly on budget", 2.0 means the budget dies in half a
    window.
  * `slo_budget_remaining{kind}` = 1 - bad/allowed, clamped at no floor
    (negative = overdrawn).

The monitor samples cumulative series into a per-kind ring of snapshots
and differences against the oldest in-window snapshot, so process-lifetime
counters become windowed rates without any new instrumentation at the
call sites. On budget exhaustion it writes one flight-recorder post-mortem
(trigger `slo_budget_exhausted`, `telemetry/flight.py`) per episode and
re-arms once the budget recovers — the dump carries the span/net rings
that explain WHY latency degraded, not just that it did.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from collections import deque

from ..telemetry import flight as _flight
from ..telemetry import metrics as _tm
from ..utils.config import SLOConfig

_REG = _tm.registry()
_BURN = _REG.gauge(
    "slo_burn_rate",
    "Error-budget burn rate per job kind over the SLO window (1.0 = "
    "exactly on budget; >1 the budget dies before the window does)",
    ("kind",),
)
_BUDGET = _REG.gauge(
    "slo_budget_remaining",
    "Fraction of the windowed error budget left per job kind (negative = "
    "overdrawn; 1.0 = untouched)",
    ("kind",),
)


class SloMonitor:
    """Derives the SLO gauges from the metrics registry. `now` is
    injectable for window tests (same pattern as the scheduler clock)."""

    def __init__(self, cfg: SLOConfig, now=time.monotonic):
        self.cfg = cfg
        self._now = now
        self._lock = threading.Lock()
        # kind -> ring of (t, cumulative_total, cumulative_bad)
        self._rings: dict[str, deque] = {}
        self._exhausted: set[str] = set()
        # baseline snapshot: jobs finished before the monitor existed
        # belong to no window — a kind's ring is seeded from this when it
        # first shows up in a sample
        self._base = self._cumulative()

    # -- cumulative reads off the registry ----------------------------------

    def _cumulative(self) -> dict[str, tuple[int, int]]:
        """{kind: (total, bad)} from the job_seconds{kind} histogram."""
        fam = _REG.family("job_seconds")
        out: dict[str, tuple[int, int]] = {}
        if fam is None:
            return out
        for values, child in fam.items():
            kind = dict(zip(fam.labelnames, values)).get("kind")
            if kind is None:
                continue
            target = self.cfg.target_for(kind)
            i = bisect_right(fam.buckets, target) - 1
            good = sum(child.counts[: i + 1])
            out[kind] = (child.count, child.count - good)
        return out

    # -- the sampler ---------------------------------------------------------

    def sample(self) -> dict:
        """Advance every kind's window, refresh the gauges, and return the
        `/slo` / `/stats` document. Cheap pure-Python dict math — safe to
        call from the event loop."""
        t = self._now()
        kinds_doc: dict[str, dict] = {}
        with self._lock:
            cum = self._cumulative()
            # kinds with explicit targets are reported even before their
            # first job, so dashboards see the objective exists
            for kind, _ in self.cfg.targets:
                cum.setdefault(kind, (0, 0))
            for kind, (total, bad) in sorted(cum.items()):
                ring = self._rings.get(kind)
                if ring is None:
                    ring = self._rings[kind] = deque()
                    bt, bb = self._base.get(kind, (0, 0))
                    ring.append((t, bt, bb))
                ring.append((t, total, bad))
                while len(ring) > 1 and t - ring[0][0] > self.cfg.window_s:
                    ring.popleft()
                t0, total0, bad0 = ring[0]
                wtotal = total - total0
                wbad = bad - bad0
                kinds_doc[kind] = self._judge(kind, wtotal, wbad)
        return {
            "enabled": True,
            "objective": self.cfg.objective,
            "windowS": self.cfg.window_s,
            "sampleS": self.cfg.sample_s,
            "kinds": kinds_doc,
        }

    def _judge(self, kind: str, wtotal: int, wbad: int) -> dict:
        allowed = (1.0 - self.cfg.objective) * wtotal
        if wtotal <= 0:
            burn, remaining = 0.0, 1.0
        elif allowed > 0:
            burn = (wbad / wtotal) / (1.0 - self.cfg.objective)
            remaining = 1.0 - wbad / allowed
        else:
            # objective == 1.0: zero budget — any bad job exhausts it
            burn = 0.0 if wbad == 0 else float(wbad)
            remaining = 1.0 if wbad == 0 else -float(wbad)
        _BURN.labels(kind=kind).set(burn)
        _BUDGET.labels(kind=kind).set(remaining)
        exhausted = wtotal > 0 and remaining <= 0.0
        if exhausted and kind not in self._exhausted:
            self._exhausted.add(kind)
            _flight.dump_soon(
                "slo_budget_exhausted",
                extra={
                    "kind": kind,
                    "targetS": self.cfg.target_for(kind),
                    "objective": self.cfg.objective,
                    "windowS": self.cfg.window_s,
                    "windowTotal": wtotal,
                    "windowBad": wbad,
                    "burnRate": burn,
                },
            )
        elif not exhausted:
            self._exhausted.discard(kind)
        return {
            "targetS": self.cfg.target_for(kind),
            "windowTotal": wtotal,
            "windowBad": wbad,
            "burnRate": round(burn, 4),
            "budgetRemaining": round(remaining, 4),
            "exhausted": exhausted,
        }


def disabled_doc() -> dict:
    """The `/stats`/`/slo` shape when no SLO is configured."""
    return {"enabled": False}
