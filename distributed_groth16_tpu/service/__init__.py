"""Proof-job service layer: async queue, bounded workers, packed-CRS cache.

Turns the one-shot proving API into a serving stack (docs/SERVICE.md):
requests enqueue `ProofJob`s, a bounded `WorkerPool` executes them off the
request path through one `ProofExecutor` proving funnel, and the
`CrsCache` skips `pack_proving_key` for repeat proofs on a hot circuit.
"""

from .crs_cache import CrsCache
from .jobs import JobCancelled, JobState, ProofJob, error_dto
from .journal import JobJournal, JournalEntry, read_journal
from .queue import JobQueue, QueueFullError
from .slo import SloMonitor
from .worker import ProofExecutor, WorkerPool

__all__ = [
    "CrsCache",
    "JobCancelled",
    "JobJournal",
    "JobQueue",
    "JobState",
    "JournalEntry",
    "ProofExecutor",
    "ProofJob",
    "QueueFullError",
    "SloMonitor",
    "WorkerPool",
    "error_dto",
    "read_journal",
]
