"""Packed-CRS LRU cache.

`pack_proving_key` is the dominant fixed cost of an MPC proof on a warm
circuit (the r4 profile put CRS packing at 84% of million-2^13 wall-clock
before the scalar route): it depends only on the stored proving key and
the packing params, not on the witness — so repeat proofs on a hot
circuit can skip it entirely. Entries are keyed by (circuit_id, packing
params); distinct packing factors on one circuit are distinct entries.

Thread-safety + single-flight: worker threads race on a hot key, and
packing is seconds-to-minutes, so the first thread to miss becomes the
leader (computes outside the lock) while followers wait on a per-key
event and then read the cached value — N concurrent proofs on one
circuit cost exactly one pack. A leader failure wakes followers, which
retry leadership so one transient fault doesn't poison the key.

Hit/miss/eviction counters feed `/stats`.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable

from ..telemetry import metrics as _tm

# Process-wide counters (docs/OBSERVABILITY.md) — the /metrics view of the
# per-instance ints below. A process runs one service cache, so summing
# across instances (tests build throwaways) is the intended semantics.
_REG = _tm.registry()
_HITS = _REG.counter("crs_cache_hits_total", "Packed-CRS cache hits")
_MISSES = _REG.counter("crs_cache_misses_total", "Packed-CRS cache misses")
_EVICTIONS = _REG.counter(
    "crs_cache_evictions_total", "Packed-CRS cache LRU evictions"
)


class CrsCache:
    def __init__(self, capacity: int = 8):
        self.capacity = capacity
        self._data: OrderedDict[Any, Any] = OrderedDict()
        self._pending: dict[Any, threading.Event] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_pack(self, key: Any, factory: Callable[[], Any]) -> Any:
        """Return the cached value for `key`, computing it with `factory`
        on a miss. Concurrent callers on one missing key run `factory`
        once. With capacity 0, caching is disabled and every call packs."""
        if self.capacity <= 0:
            with self._lock:
                self.misses += 1
            _MISSES.inc()
            return factory()
        while True:
            with self._lock:
                if key in self._data:
                    self._data.move_to_end(key)
                    self.hits += 1
                    _HITS.inc()
                    return self._data[key]
                ev = self._pending.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._pending[key] = ev
                    self.misses += 1
                    _MISSES.inc()
                    break  # we are the leader
            # follower: wait for the leader, then re-check (a dead leader
            # leaves the key absent and we retry for leadership)
            ev.wait()
        try:
            value = factory()
        except BaseException:
            with self._lock:
                del self._pending[key]
            ev.set()
            raise
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1
                _EVICTIONS.inc()
            del self._pending[key]
        ev.set()
        return value

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._data

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._data),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hitRate": (self.hits / total) if total else None,
            }
