"""Asyncio job queue with admission control — the backpressure layer.

The queue is also the job registry and the service's bookkeeping core:
every submitted job stays addressable by id for status/result/cancel, and
completion statistics (counters, runtime EMA, merged phase timings) feed
both the `/stats` route and the retryAfter hint on rejections.

Admission control: at most `bound` jobs may be waiting (QUEUED). A submit
past that raises `QueueFullError` carrying a `retry_after_s` hint — the
API maps it to HTTP 429 — estimated as (depth / workers) x the observed
mean runtime of jobs in the rejected job's BUCKET (kind + circuit + l,
`ProofJob.bucket`), so a slow big circuit doesn't inflate hints for small
ones; unknown buckets fall back to the cross-bucket mean, and cold start
to a configured constant.

Everything here runs on the event-loop thread except `record_timings`
(PhaseTimings is internally locked), so plain attributes suffice.
"""

from __future__ import annotations

import asyncio
import math
from collections import deque

from ..telemetry import metrics as _tm
from ..utils.timers import PhaseTimings
from .jobs import JobState, ProofJob, error_dto

# Queue-shape metrics (docs/OBSERVABILITY.md). Process-wide like the rest
# of the registry: a process runs one service, so queue gauges are global.
_REG = _tm.registry()
_SUBMITTED = _REG.counter("jobs_submitted_total", "Jobs admitted to the queue")
_REJECTED = _REG.counter(
    "jobs_rejected_total", "Jobs rejected at the admission bound (HTTP 429)"
)
_FINISHED = _REG.counter(
    "jobs_finished_total", "Jobs reaching a terminal state", ("state",)
)
_DEPTH = _REG.gauge("job_queue_depth", "Jobs currently waiting (QUEUED)")
_RUNNING = _REG.gauge("job_queue_running", "Jobs currently executing")
_RUNTIME_EMA = _REG.gauge(
    "job_runtime_ema_seconds",
    "Exponential moving average of job runtime, per bucket — the "
    "retryAfter estimator (a slow big circuit must not inflate hints "
    "for small ones)",
    ("bucket",),
)
_QUEUE_WAIT = _REG.histogram(
    "job_queue_wait_seconds", "Seconds a job waited QUEUED before starting"
)
_JOB_SECONDS = _REG.histogram(
    "job_seconds", "End-to-end job runtime (RUNNING to terminal), per kind",
    ("kind",),
)


class QueueFullError(Exception):
    """Structured rejection: the queue is at its admission bound."""

    def __init__(self, bound: int, depth: int, retry_after_s: float):
        self.bound = bound
        self.depth = depth
        self.retry_after_s = retry_after_s
        super().__init__(
            f"job queue full ({depth}/{bound} queued); "
            f"retry in ~{retry_after_s:.0f}s"
        )


class JobQueue:
    def __init__(
        self,
        bound: int = 64,
        workers: int = 2,
        retry_after_s: float = 5.0,
        history_bound: int = 1024,
        journal=None,
    ):
        self.bound = bound
        self.workers = max(1, workers)
        self.default_retry_after_s = retry_after_s
        # optional durable job journal (service/journal.py): every
        # admission and state transition flowing through the queue is
        # recorded, so a crashed replica's successor can replay
        self.journal = journal
        # terminal jobs stay addressable for status polling, but only the
        # `history_bound` most recent — without eviction the registry (and
        # every result payload) grows without bound on a long-lived service
        self.history_bound = history_bound
        self._terminal_order: deque[str] = deque()
        self.jobs: dict[str, ProofJob] = {}
        self._q: asyncio.Queue[ProofJob] = asyncio.Queue()
        self._queued_ids: set[str] = set()
        self._running_ids: set[str] = set()
        # counters for /stats
        self.submitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        # runtime EMA per bucket (jobs.ProofJob.bucket): retryAfter hints
        # are estimated from jobs of the SAME shape, so a slow big circuit
        # doesn't inflate the hint for a small one queued behind it
        self._runtime_ema_s: dict[str, float] = {}
        self.aggregate_timings = PhaseTimings()

    # -- submission (request path) ------------------------------------------

    def submit(self, job: ProofJob) -> ProofJob:
        """Synchronous admission (tests, startup replay — no traffic to
        stall). The request path uses submit_async so the journal fsync
        happens off the event loop."""
        self._admit_or_reject(job)
        if self.journal is not None:
            # durability BEFORE admission: once the caller sees a 202 the
            # job survives a crash (WAL contract, service/journal.py)
            self.journal.append_submit(job)
        self._enqueue(job)
        return job

    async def submit_async(self, job: ProofJob) -> ProofJob:
        """Request-path admission: the journal append (base64 of a
        payload up to the 100 MB body cap + write + fsync) must not run
        on the event loop — one big upload would stall /healthz,
        heartbeats, and every concurrent request. The admission slot is
        reserved BEFORE the thread hop so the 429 bound holds exactly
        under concurrent submissions, and returned on a failed append."""
        self._admit_or_reject(job)
        self.jobs[job.id] = job
        self._queued_ids.add(job.id)
        _DEPTH.set(len(self._queued_ids))
        if self.journal is not None:
            try:
                await asyncio.to_thread(self.journal.append_submit, job)
            except BaseException:
                self._queued_ids.discard(job.id)
                del self.jobs[job.id]
                _DEPTH.set(len(self._queued_ids))
                raise
            if job.state.terminal:
                # a DELETE landed during the append hop: cancel() found
                # the id missing from the journal and its CANCELLED
                # record was dropped — write the terminal record now or
                # the entry stays live forever and the next boot
                # resurrects a deliberately cancelled job
                await asyncio.to_thread(
                    self.journal.append_state, job.id, job.state, job.error
                )
                self.submitted += 1
                _SUBMITTED.inc()
                return job
        self._q.put_nowait(job)
        self.submitted += 1
        _SUBMITTED.inc()
        return job

    def _admit_or_reject(self, job: ProofJob) -> None:
        depth = len(self._queued_ids)
        if depth >= self.bound:
            self.rejected += 1
            _REJECTED.inc()
            raise QueueFullError(
                self.bound, depth, self.retry_after_hint(job.bucket)
            )

    def _enqueue(self, job: ProofJob) -> None:
        self.jobs[job.id] = job
        self._queued_ids.add(job.id)
        self._q.put_nowait(job)
        self.submitted += 1
        _SUBMITTED.inc()
        _DEPTH.set(len(self._queued_ids))

    def retry_after_hint(self, bucket: str | None = None) -> float:
        """Seconds until a queue slot plausibly frees: one full drain of
        the current backlog through the worker pool at the observed mean
        runtime of jobs in the SAME bucket. Unknown bucket (or none
        given) falls back to the mean across buckets; cold start falls
        back to the configured constant."""
        ema = self._runtime_ema_s.get(bucket) if bucket is not None else None
        if ema is None and self._runtime_ema_s:
            ema = sum(self._runtime_ema_s.values()) / len(self._runtime_ema_s)
        if ema is None:
            return self.default_retry_after_s
        drains = math.ceil((len(self._queued_ids) + 1) / self.workers)
        return max(1.0, drains * ema)

    # -- worker side ---------------------------------------------------------

    async def get(self) -> ProofJob:
        job = await self._q.get()
        self._queued_ids.discard(job.id)
        _DEPTH.set(len(self._queued_ids))
        return job

    def on_started(self, job: ProofJob) -> None:
        self._running_ids.add(job.id)
        _RUNNING.set(len(self._running_ids))
        if self.journal is not None:
            self.journal.append_state(job.id, JobState.RUNNING)
        if job.started_at is not None:
            _QUEUE_WAIT.observe(job.started_at - job.created_at)

    def on_finished(self, job: ProofJob) -> None:
        self._running_ids.discard(job.id)
        _RUNNING.set(len(self._running_ids))
        if job.state is JobState.DONE:
            self.completed += 1
        elif job.state is JobState.FAILED:
            self.failed += 1
        elif job.state is JobState.CANCELLED:
            self.cancelled += 1
        _FINISHED.labels(state=job.state.value).inc()
        if self.journal is not None:
            # idempotent: the shutdown paths (fail_terminal) journal the
            # terminal record first, and the journal drops a second
            # terminal append for an id it no longer holds live
            self.journal.append_state(job.id, job.state, error=job.error)
        rt = job.runtime_s
        if rt is not None:
            b = job.bucket
            prev = self._runtime_ema_s.get(b)
            self._runtime_ema_s[b] = (
                rt if prev is None else 0.7 * prev + 0.3 * rt
            )
            _RUNTIME_EMA.labels(bucket=b).set(self._runtime_ema_s[b])
            _JOB_SECONDS.labels(kind=job.kind).observe(rt)
        self.aggregate_timings.merge(job.timings)
        self._note_terminal(job)

    def _note_terminal(self, job: ProofJob) -> None:
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.history_bound:
            jid = self._terminal_order.popleft()
            j = self.jobs.get(jid)
            if j is not None and j.state.terminal:
                del self.jobs[jid]

    def drain_pending(self) -> list[ProofJob]:
        """Pop every still-QUEUED job (shutdown path): the caller owns
        transitioning them to a terminal state so sync waiters and status
        pollers don't see QUEUED forever."""
        out = []
        while True:
            try:
                job = self._q.get_nowait()
            except asyncio.QueueEmpty:
                break
            self._queued_ids.discard(job.id)
            if job.state is JobState.QUEUED:
                out.append(job)
        _DEPTH.set(len(self._queued_ids))
        return out

    # -- control plane -------------------------------------------------------

    def cancel(self, job_id: str) -> ProofJob | None:
        """Cancel a job. QUEUED jobs flip to CANCELLED immediately and are
        skipped when popped; RUNNING jobs get a cooperative cancel request
        honored at the executor's next phase boundary. Terminal jobs are a
        no-op. Returns the job, or None if unknown."""
        job = self.jobs.get(job_id)
        if job is None:
            return None
        if job.state is JobState.QUEUED:
            self._queued_ids.discard(job.id)
            _DEPTH.set(len(self._queued_ids))
            job.request_cancel()
            if self.journal is not None:
                # durable first: a crash right here must not resurrect a
                # job the operator deliberately cancelled
                self.journal.append_state(job.id, JobState.CANCELLED)
            job.mark_cancelled()
            self.cancelled += 1
            _FINISHED.labels(state=JobState.CANCELLED.value).inc()
            self._note_terminal(job)
        elif job.state is JobState.RUNNING:
            job.request_cancel()
        return job

    def fail_terminal(self, job: ProofJob, exc: BaseException) -> None:
        """Shutdown-drain path (WorkerPool.stop / BatchScheduler.stop):
        journal the terminal failure BEFORE the in-memory transition. The
        old order (mark_failed, then the on_finished journal write) left
        a crash window in which a deliberately failed job was still
        QUEUED on disk — the next boot would resurrect it."""
        if self.journal is not None:
            self.journal.append_state(
                job.id, JobState.FAILED, error=error_dto(exc)
            )
        job.mark_failed(exc)
        self.on_finished(job)

    def stats(self) -> dict:
        return {
            "queueDepth": len(self._queued_ids),
            "queueBound": self.bound,
            "workers": self.workers,
            "running": len(self._running_ids),
            "submitted": self.submitted,
            "rejected": self.rejected,
            "completed": self.completed,
            "failed": self.failed,
            "cancelled": self.cancelled,
            # the runtime EMAs feeding retry_after_hint, exposed both here
            # and as the job_runtime_ema_seconds{bucket} gauge on /metrics;
            # meanRuntimeS keeps its pre-bucketing shape (None until the
            # first job completes) as the cross-bucket mean
            "meanRuntimeS": (
                sum(self._runtime_ema_s.values()) / len(self._runtime_ema_s)
                if self._runtime_ema_s
                else None
            ),
            "runtimeEmaByBucket": dict(self._runtime_ema_s),
            "phases": self.aggregate_timings.as_millis(),
        }
