"""Durable job journal: the write-ahead log that makes the service plane
crash-safe.

Everything the service knows about a job — the `JobQueue` registry, the
bucketer, the worker pool — lives in process memory, so a replica crash
or redeploy used to silently drop every accepted job. The journal fixes
that with the classic WAL shape:

  * every **submission** is appended (id, kind, circuit, l, the raw
    multipart payload base64'd) and fsynced BEFORE the job is admitted —
    a 202 response means the job survives a crash;
  * every **state transition** (RUNNING, DONE, FAILED, CANCELLED, a
    quarantine mark) is appended as it happens;
  * records live in numbered JSONL **segments**; when the active segment
    exceeds `segment_records`, a **compaction** rewrites only the live
    (non-terminal) jobs into a fresh segment and deletes the old ones —
    terminal jobs cost zero bytes at steady state;
  * on startup the service **replays**: non-terminal, non-quarantined
    jobs (`pending()`) are rebuilt and re-submitted idempotently by job
    id — a job interrupted mid-RUNNING simply proves again.

Threading: every method takes an internal lock, so appends are safe
from any thread. The payload-bearing submit appends — and the
compaction only they may trigger, a rewrite of every live payload — run
on a worker thread (`JobQueue.submit_async`); the small
state-transition appends run on the event-loop thread, paying one
bounded fsync each. Each append is one `write + flush + fsync` (fsync
is the durability contract; `fsync=False` trades it away for tests and
throwaway replicas).

Record grammar (one JSON object per line):

  {"k": "submit", "id", "kind", "cid", "l", "t", "fields": {name: b64},
   ["tenant", "priority", "trace"]}
  {"k": "state",  "id", "state", "t", ["error": {type,message,phase}]}
  {"k": "quarantine", "id", "t", "reason"}
  {"k": "checkpoint", "t"}          # clean-shutdown marker

Last record per id wins; unknown ids in state records are ignored (they
belong to jobs already compacted away).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from ..telemetry import metrics as _tm
from .jobs import JobState

log = logging.getLogger(__name__)

_REG = _tm.registry()
_APPENDS = _REG.counter(
    "journal_appends_total",
    "Journal records durably appended, per record kind",
    ("kind",),
)
_APPEND_SECONDS = _REG.histogram(
    "journal_append_seconds",
    "Wall seconds per journal append (write + flush + fsync) — the "
    "durability lag every admission pays",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
)
_REPLAYED = _REG.counter(
    "journal_replayed_total",
    "Jobs re-enqueued by startup replay, per journaled state",
    ("state",),
)
_COMPACTIONS = _REG.counter(
    "journal_compactions_total", "Segment compactions (rotation + rewrite)"
)
_LIVE = _REG.gauge(
    "journal_live_records", "Non-terminal jobs currently in the journal"
)

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".jsonl"


def _submit_record(e: "JournalEntry") -> dict:
    """The submit-record shape — shared by the live append and the
    compaction rewrite so the two can never drift."""
    rec = {
        "k": "submit",
        "id": e.id,
        "kind": e.kind,
        "cid": e.circuit_id,
        "l": e.l,
        "t": e.created_at,
        "fields": _encode_fields(e.fields),
    }
    if e.tenant:
        rec["tenant"] = e.tenant
    if e.priority:
        rec["priority"] = e.priority
    if e.trace_id:
        rec["trace"] = e.trace_id
    return rec

_TERMINAL = {JobState.DONE.value, JobState.FAILED.value, JobState.CANCELLED.value}


@dataclass
class JournalEntry:
    """One live job as the journal knows it (the replay unit)."""

    id: str
    kind: str
    circuit_id: str
    l: int
    created_at: float
    fields: dict[str, bytes] = field(default_factory=dict, repr=False)
    state: str = JobState.QUEUED.value
    quarantined: bool = False
    # fleet metadata (docs/FLEET.md): a handoff must re-route the job
    # under the tenant that submitted it, so identity rides the WAL —
    # and under the same end-to-end trace id, so the re-proved job's
    # spans still stitch into the trace the router minted
    tenant: str = ""
    priority: str = ""
    trace_id: str = ""

    @property
    def replayable(self) -> bool:
        return self.state not in _TERMINAL and not self.quarantined


def _encode_fields(fields: dict[str, bytes]) -> dict[str, str]:
    return {k: base64.b64encode(v).decode("ascii") for k, v in fields.items()}


def _decode_fields(enc: dict[str, str]) -> dict[str, bytes]:
    return {k: base64.b64decode(v) for k, v in enc.items()}


def _segment_names(directory: str) -> list[str]:
    return sorted(
        n for n in os.listdir(directory)
        if n.startswith(_SEGMENT_PREFIX) and n.endswith(_SEGMENT_SUFFIX)
    )


def _apply_record(
    live: dict[str, JournalEntry], tombstones: set[str], rec: dict
) -> None:
    k = rec.get("k")
    if k == "submit":
        # tombstone guard (crash-window consistency): a compaction that
        # died after fsyncing its snapshot but before its pending-flush
        # leaves a NEW segment restating the submit of a job whose
        # terminal record is only in the OLD segment — replay must not
        # let the later submit resurrect the finished job
        if rec["id"] in tombstones:
            return
        live[rec["id"]] = JournalEntry(
            id=rec["id"],
            kind=rec["kind"],
            circuit_id=rec["cid"],
            l=int(rec.get("l", 2)),
            created_at=float(rec.get("t", 0.0)),
            fields=_decode_fields(rec.get("fields", {})),
            tenant=rec.get("tenant", ""),
            priority=rec.get("priority", ""),
            trace_id=rec.get("trace", ""),
        )
    elif k == "state":
        e = live.get(rec.get("id"))
        if e is None:
            return
        state = rec.get("state", "")
        if state in _TERMINAL:
            del live[e.id]
            tombstones.add(e.id)
        else:
            e.state = state
    elif k == "quarantine":
        e = live.get(rec.get("id"))
        if e is not None:
            e.quarantined = True
    # "checkpoint" records carry no state — they only mark clean exits


def _load_segments(
    directory: str,
) -> tuple[dict[str, JournalEntry], int, int]:
    """Parse every segment (crash state included) into the live map.
    Returns (live entries, highest segment number, records seen) — the
    shared loader behind both a real JobJournal open and the read-only
    `read_journal` inspection path."""
    live: dict[str, JournalEntry] = {}
    tombstones: set[str] = set()
    seg_no = 0
    records = 0
    for name in _segment_names(directory):
        seg_no = max(
            seg_no, int(name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)])
        )
        with open(os.path.join(directory, name), "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # a torn final line is the expected crash artifact:
                    # everything before it was fsynced and parses
                    log.warning("journal: dropping torn record in %s", name)
                    continue
                _apply_record(live, tombstones, rec)
                records += 1
    return live, seg_no, records


class JobJournal:
    """Append-only WAL of job submissions + transitions under `directory`.

    Opening loads every existing segment (crash state included) and
    starts a fresh segment for new appends; `pending()` is what a replay
    should re-enqueue. All appends are idempotent by job id: a submit
    for a known-live id degrades to a requeue state record, terminal
    records for unknown ids are dropped.
    """

    def __init__(
        self,
        directory: str,
        fsync: bool = True,
        segment_records: int = 4096,
    ):
        self.directory = directory
        self.fsync = fsync
        self.segment_records = max(16, segment_records)
        self._lock = threading.Lock()
        self._live: dict[str, JournalEntry] = {}
        self._fh = None
        self._records = 0
        self._seg_no = 0
        # snapshot-and-swap compaction state: while a compaction encodes
        # the (potentially payload-heavy) live set WITHOUT the lock,
        # concurrent appends keep landing in the old segment and are
        # additionally stashed here so the new segment replays them
        self._compacting = False
        self._compact_pending: list[str] = []
        os.makedirs(directory, exist_ok=True)
        self._load_existing()
        self._open_segment(self._seg_no + 1)
        _LIVE.set(len(self._live))

    # -- startup -------------------------------------------------------------

    def _segments(self) -> list[str]:
        return _segment_names(self.directory)

    def _load_existing(self) -> None:
        self._live, self._seg_no, self._records = _load_segments(
            self.directory
        )

    # -- the write path ------------------------------------------------------

    def _open_segment(self, n: int) -> None:
        self._seg_no = n
        path = os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}{n:08d}{_SEGMENT_SUFFIX}"
        )
        self._fh = open(path, "a", encoding="utf-8")
        self._records = 0

    def _append(self, rec: dict, kind: str) -> bool:
        """Write one record (caller holds the lock). Returns True when
        the segment is ripe for compaction — the CALLER decides whether
        to run one (only the submit path does: a compaction rewrites
        EVERY live submission payload, far too heavy for the loop-side
        state appends; queue.submit_async runs it on a worker thread)."""
        t0 = time.monotonic()
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._fh.write(line)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        if self._compacting:
            self._compact_pending.append(line)
        self._records += 1
        _APPENDS.labels(kind=kind).inc()
        _APPEND_SECONDS.observe(time.monotonic() - t0)
        # ripe only when at least half the segment is reclaimable: a
        # bare records >= segment_records trigger would re-compact on
        # every append once the live set outgrew the segment bound —
        # O(live set) rewrite+fsync per admission instead of amortized
        # O(1)
        return self._records >= max(
            self.segment_records, 4 * len(self._live)
        )

    def append_submit(self, job) -> None:
        """Durably record one admission BEFORE the queue accepts it. For
        an id the journal already holds live (a startup replay
        re-submitting) this degrades to a requeue state record instead of
        duplicating the payload."""
        with self._lock:
            if job.id in self._live:
                self._live[job.id].state = JobState.QUEUED.value
                ripe = self._append(
                    {"k": "state", "id": job.id,
                     "state": JobState.QUEUED.value, "t": time.time()},
                    "state",
                )
            else:
                e = JournalEntry(
                    id=job.id,
                    kind=job.kind,
                    circuit_id=job.circuit_id,
                    l=job.l,
                    created_at=job.created_at,
                    fields=dict(job.fields),
                    tenant=getattr(job, "tenant", ""),
                    priority=getattr(job, "priority", ""),
                    trace_id=getattr(job, "trace_id", ""),
                )
                self._live[job.id] = e
                ripe = self._append(_submit_record(e), "submit")
            _LIVE.set(len(self._live))
        if ripe:
            self._compact()

    def append_state(
        self, job_id: str, state: JobState, error: dict | None = None
    ) -> None:
        """Record one transition. Terminal records drop the job from the
        live set (idempotent: a second terminal append for the same id is
        a no-op — the shutdown paths journal BEFORE the in-memory
        transition, then the normal on_finished path fires again)."""
        with self._lock:
            e = self._live.get(job_id)
            if e is None:
                return
            rec: dict = {"k": "state", "id": job_id,
                         "state": state.value, "t": time.time()}
            if error is not None:
                rec["error"] = error
            if state.terminal:
                del self._live[job_id]
            else:
                e.state = state.value
            self._append(rec, "state")
            _LIVE.set(len(self._live))

    def append_quarantine(self, job_id: str, reason: str) -> None:
        """Mark a poisoned job: it stays in the journal until its terminal
        record lands, but a replay that finds the mark (crash between the
        two appends) must NOT resurrect it."""
        with self._lock:
            e = self._live.get(job_id)
            if e is None:
                return
            e.quarantined = True
            self._append(
                {"k": "quarantine", "id": job_id, "reason": reason,
                 "t": time.time()},
                "quarantine",
            )

    # -- compaction ----------------------------------------------------------

    def _compact(self) -> None:
        """Rewrite only the live jobs into a fresh segment and delete the
        old ones. Snapshot-and-swap: the payload-heavy encode+write of
        the live set happens WITHOUT the lock (concurrent loop-side
        state appends keep landing in the old segment and are stashed
        for replay into the new one), and the lock is only held for the
        snapshot and the final pending-flush + swap. Crash-ordered: the
        new segment is fully written and fsynced before any old segment
        is unlinked, so every crash window leaves at least one complete
        copy of the live set on disk. Replaying old + partial new is
        consistent: the snapshot only restates the old segments, and the
        one divergence — a job whose concurrent terminal record reached
        only the old segment while the new one restates its submit — is
        closed by the loader's tombstone guard (_apply_record)."""
        with self._lock:
            if self._fh is None or self._compacting:
                return
            self._compacting = True
            self._compact_pending = []
            # quarantined entries are terminal-in-spirit: they exist
            # only so a crash between the quarantine mark and the FAILED
            # record can't resurrect the poison. Compaction purges them —
            # without this, one such crash would leave a permanent live
            # record that survives every checkpoint.
            for jid in [e.id for e in self._live.values() if e.quarantined]:
                del self._live[jid]
            snapshot = list(self._live.values())
            old = self._segments()
            new_no = self._seg_no + 1
            _LIVE.set(len(self._live))
        path = os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}{new_no:08d}{_SEGMENT_SUFFIX}"
        )
        nfh = open(path, "a", encoding="utf-8")
        n = 0
        for e in snapshot:
            nfh.write(json.dumps(
                _submit_record(e), separators=(",", ":")
            ) + "\n")
            n += 1
            state = e.state  # one read: may be mutated by a live append,
            # whose record is then in _compact_pending and replayed below
            if state != JobState.QUEUED.value:
                nfh.write(json.dumps(
                    {"k": "state", "id": e.id, "state": state,
                     "t": time.time()},
                    separators=(",", ":"),
                ) + "\n")
                n += 1
        nfh.flush()
        if self.fsync:
            os.fsync(nfh.fileno())
        with self._lock:
            for line in self._compact_pending:
                nfh.write(line)
                n += 1
            nfh.flush()
            if self.fsync:
                os.fsync(nfh.fileno())
            old_fh, self._fh = self._fh, nfh
            self._seg_no = new_no
            self._records = n
            self._compacting = False
            self._compact_pending = []
        old_fh.close()
        mine = os.path.basename(path)
        for name in old:
            if name != mine:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass
        _COMPACTIONS.inc()

    def checkpoint(self) -> None:
        """Clean-shutdown compaction: rewrite the live set (empty after a
        full drain) and stamp a checkpoint marker, so the next boot
        replays exactly the jobs that were still owed work."""
        self._compact()
        with self._lock:
            if self._fh is not None:
                self._append({"k": "checkpoint", "t": time.time()},
                             "checkpoint")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- replay --------------------------------------------------------------

    def pending(self) -> list[JournalEntry]:
        """The jobs a startup replay should re-enqueue: journaled
        non-terminal (QUEUED or interrupted RUNNING), not quarantined,
        oldest first."""
        with self._lock:
            out = [e for e in self._live.values() if e.replayable]
        return sorted(out, key=lambda e: e.created_at)

    def note_replayed(self, state: str) -> None:
        """Count one replayed job by the state the crash interrupted.
        Takes the pre-captured state STRING, not the entry: re-submission
        requeues the live entry in place, so reading entry.state after
        submit would always say QUEUED."""
        _REPLAYED.labels(state=state).inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "liveRecords": len(self._live),
                "segment": self._seg_no,
                "segmentRecords": self._records,
                "fsync": self.fsync,
            }


def read_journal(directory: str) -> list[JournalEntry]:
    """Read-only replay preview of a journal directory — the
    `dg16-cli job recover` path. Never writes: parses every segment and
    returns ALL live entries (callers filter on `.replayable`). Safe to
    run against a crashed replica's store."""
    if not os.path.isdir(directory):
        return []
    live, _, _ = _load_segments(directory)
    return sorted(live.values(), key=lambda e: e.created_at)
