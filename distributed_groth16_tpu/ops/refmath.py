"""Pure-Python reference math for BN254 — the ground truth every JAX/Pallas
kernel is differentially tested against (mirrors the reference's strategy of
checking each distributed kernel against its single-node arkworks counterpart,
e.g. dist-primitives/src/dfft/mod.rs:304, dist-primitives/examples/dmsm_test.rs).

Everything here is host-side Python bigint code: slow, simple, obviously
correct. Device code lives in ops/field.py, ops/ntt.py, ops/curve.py.
"""

from __future__ import annotations

from .constants import (
    FR_GENERATOR,
    FR_TWO_ADICITY,
    G1_B,
    G2_B,
    Q,
    R,
)

# ---------------------------------------------------------------------------
# Prime field helpers (work for any modulus)
# ---------------------------------------------------------------------------


def finv(x: int, p: int) -> int:
    return pow(x, p - 2, p)


def batch_inv(xs, p: int):
    """Montgomery batch inversion."""
    n = len(xs)
    prefix = [1] * (n + 1)
    for i, x in enumerate(xs):
        prefix[i + 1] = prefix[i] * x % p
    inv_all = finv(prefix[n], p)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % p
        inv_all = inv_all * xs[i] % p
    return out


# ---------------------------------------------------------------------------
# Radix-2 evaluation domain over Fr — ark-poly semantics
# ---------------------------------------------------------------------------


class Domain:
    """Mirror of ark-poly Radix2EvaluationDomain — over BN254 Fr by
    default, or any prime scalar field via (modulus, generator) (the
    reference instantiates domains over BLS12-377 Fr too,
    dist-primitives/examples/dmsm_bench.rs:46).

    fft(coeffs)  : evaluate at offset * w^i for i in 0..size
    ifft(evals)  : inverse; inputs shorter than size are zero-padded (ark
                   semantics: fft_in_place resizes with zeros).
    get_coset(g) : same group generator, offset multiplied in.
    """

    def __init__(self, size: int, offset: int = 1,
                 modulus: int = R, generator: int = FR_GENERATOR):
        assert size & (size - 1) == 0, "domain size must be a power of two"
        r = modulus
        two_adicity = ((r - 1) & -(r - 1)).bit_length() - 1
        assert size <= (1 << two_adicity)
        self.size = size
        self.r = r
        self.generator = generator
        self.offset = offset % r
        self.group_gen = pow(generator, (r - 1) // size, r)
        self.group_gen_inv = finv(self.group_gen, r)
        self.size_inv = finv(size, r)
        self.offset_inv = finv(self.offset, r) if offset != 1 else 1

    def get_coset(self, offset: int) -> "Domain":
        return Domain(self.size, offset * self.offset % self.r,
                      self.r, self.generator)

    def elements(self):
        w, acc = self.group_gen, self.offset
        out = []
        for _ in range(self.size):
            out.append(acc)
            acc = acc * w % self.r
        return out

    def _pad(self, v):
        v = [x % self.r for x in v]
        assert len(v) <= self.size
        return v + [0] * (self.size - len(v))

    def fft(self, coeffs):
        r = self.r
        c = self._pad(coeffs)
        if self.offset != 1:
            mul, off = 1, self.offset
            for i in range(self.size):
                c[i] = c[i] * mul % r
                mul = mul * off % r
        return _ntt(c, self.group_gen, r)

    def ifft(self, evals):
        r = self.r
        e = self._pad(evals)
        c = _ntt(e, self.group_gen_inv, r)
        c = [x * self.size_inv % r for x in c]
        if self.offset != 1:
            mul, off_inv = 1, self.offset_inv
            for i in range(self.size):
                c[i] = c[i] * mul % r
                mul = mul * off_inv % r
        return c


def bit_reverse_permute(v):
    n = len(v)
    logn = n.bit_length() - 1
    out = list(v)
    for i in range(n):
        j = int(format(i, f"0{logn}b")[::-1], 2) if logn else 0
        if j > i:
            out[i], out[j] = out[j], out[i]
    return out


def _ntt(v, w, r: int = R):
    """Iterative radix-2 Cooley-Tukey NTT (DIT, natural in/natural out)."""
    n = len(v)
    v = bit_reverse_permute(v)
    span = 1
    while span < n:
        wspan = pow(w, n // (2 * span), r)
        for start in range(0, n, 2 * span):
            wj = 1
            for j in range(span):
                a = v[start + j]
                b = v[start + j + span] * wj % r
                v[start + j] = (a + b) % r
                v[start + j + span] = (a - b) % r
                wj = wj * wspan % r
        span *= 2
    return v


# ---------------------------------------------------------------------------
# Fq2 arithmetic (for G2): Fq[u] / (u^2 + 1)
# ---------------------------------------------------------------------------


def fq2_add(a, b):
    return ((a[0] + b[0]) % Q, (a[1] + b[1]) % Q)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % Q, (a[1] - b[1]) % Q)


def fq2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
    t0 = a[0] * b[0] % Q
    t1 = a[1] * b[1] % Q
    return ((t0 - t1) % Q, ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % Q)


def fq2_sq(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t = a[0] * a[1] % Q
    return ((a[0] + a[1]) * (a[0] - a[1]) % Q, 2 * t % Q)


def fq2_neg(a):
    return ((-a[0]) % Q, (-a[1]) % Q)


def fq2_scalar(a, k):
    return (a[0] * k % Q, a[1] * k % Q)


def fq2_inv(a):
    # 1/(a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % Q
    ninv = finv(norm, Q)
    return (a[0] * ninv % Q, (-a[1]) * ninv % Q)


def fq2_conj(a):
    return (a[0], (-a[1]) % Q)


FQ2_ZERO = (0, 0)
FQ2_ONE = (1, 0)


# ---------------------------------------------------------------------------
# Short Weierstrass curve ops, generic over the coordinate field.
# Points are affine tuples (x, y) or None for infinity.
# ---------------------------------------------------------------------------


class _CurveOps:
    def __init__(self, add, sub, mul, sq, neg, inv, scalar, zero, one, b,
                 order=None):
        self.fadd, self.fsub, self.fmul, self.fsq = add, sub, mul, sq
        self.fneg, self.finv, self.fscalar = neg, inv, scalar
        self.zero, self.one, self.b = zero, one, b
        self.order = order if order is not None else R  # scalar group order

    def is_on_curve(self, p) -> bool:
        if p is None:
            return True
        x, y = p
        lhs = self.fsq(y)
        rhs = self.fadd(self.fmul(self.fsq(x), x), self.b)
        return lhs == rhs

    def add(self, p, q):
        if p is None:
            return q
        if q is None:
            return p
        x1, y1 = p
        x2, y2 = q
        if x1 == x2:
            if self.fadd(y1, y2) == self.zero:
                return None
            return self.double(p)
        lam = self.fmul(self.fsub(y2, y1), self.finv(self.fsub(x2, x1)))
        x3 = self.fsub(self.fsub(self.fsq(lam), x1), x2)
        y3 = self.fsub(self.fmul(lam, self.fsub(x1, x3)), y1)
        return (x3, y3)

    def double(self, p):
        if p is None:
            return None
        x, y = p
        if y == self.zero:
            return None
        lam = self.fmul(self.fscalar(self.fsq(x), 3), self.finv(self.fscalar(y, 2)))
        x3 = self.fsub(self.fsq(lam), self.fscalar(x, 2))
        y3 = self.fsub(self.fmul(lam, self.fsub(x, x3)), y)
        return (x3, y3)

    def neg(self, p):
        if p is None:
            return None
        return (p[0], self.fneg(p[1]))

    def scalar_mul(self, p, k: int):
        k %= self.order
        acc, base = None, p
        while k:
            if k & 1:
                acc = self.add(acc, base)
            base = self.double(base)
            k >>= 1
        return acc

    def msm(self, points, scalars):
        acc = None
        for p, s in zip(points, scalars):
            acc = self.add(acc, self.scalar_mul(p, s))
        return acc


def _fq_scalar(a, k):
    return a * k % Q


G1 = _CurveOps(
    add=lambda a, b: (a + b) % Q,
    sub=lambda a, b: (a - b) % Q,
    mul=lambda a, b: a * b % Q,
    sq=lambda a: a * a % Q,
    neg=lambda a: (-a) % Q,
    inv=lambda a: finv(a, Q),
    scalar=_fq_scalar,
    zero=0,
    one=1,
    b=G1_B,
)

G2 = _CurveOps(
    add=fq2_add,
    sub=fq2_sub,
    mul=fq2_mul,
    sq=fq2_sq,
    neg=fq2_neg,
    inv=fq2_inv,
    scalar=fq2_scalar,
    zero=FQ2_ZERO,
    one=FQ2_ONE,
    b=G2_B,
)
