"""Branchless elliptic-curve arithmetic for BN254 G1/G2 on JAX/TPU.

Points live on device in homogeneous projective coordinates (X : Y : Z) as
uint32 limb tensors — G1: (..., 3, 16), G2: (..., 3, 2, 16) — using the
complete addition/doubling formulas of Renes–Costello–Batina 2016 for short
Weierstrass curves with a = 0 (algorithms 7 and 9). Complete formulas have no
data-dependent branches: one fused vector program handles generic addition,
doubling, and the point at infinity (0 : 1 : 0), which is exactly what XLA
wants — static shapes, no `lax.cond` per lane.

Replaces the reference's use of arkworks ark-ec short_weierstrass group ops
(consumed throughout dist-primitives/src/dmsm/mod.rs and groth16/src/prove.rs);
there is no reference file to translate — this layer is curve math designed
for the TPU VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .constants import G1_B, G2_B, N_LIMBS, Q, R
from .field import fq, fq2


class CurvePoints:
    """Vectorized projective point ops over a generic coordinate field.

    `F` is a PrimeField (G1) or Fq2Ops (G2); `elem_shape` is the trailing
    shape of one coordinate — (16,) for Fq, (2, 16) for Fq2. A point array
    has shape (..., 3) + elem_shape.
    """

    def __init__(self, field, b, elem_shape, glv=None, scalar_order=None):
        self.F = field
        # order of the scalar group (Fr); BN254 by default
        self.r = scalar_order if scalar_order is not None else R
        self.elem_shape = elem_shape
        self.coord_axes = len(elem_shape)
        b3_int = self._triple_int(b)
        self.b = self._const(b)  # b in Montgomery form, device const
        self.b3 = self._const(b3_int)  # 3*b in Montgomery form, device const
        z, o = field.consts(())
        self._zero_c, self._one_c = z, o
        # GLV endomorphism parameters (ops/glv.py), or None when the curve
        # has no cheap endomorphism wired up (G2): fixed-scalar ladders then
        # fall back to full-width double-and-add.
        self.glv = glv
        self._beta_c = self._const(glv.beta) if glv is not None else None
        # jit the big combinational kernels once per instance. The
        # scan-shaped ones (ladders, sequential sums) MUST be jitted:
        # eagerly-dispatched scan/fori executables are an XLA:CPU crash
        # class here (backend_compile_and_load segfault once enough
        # executables are live in a long-lived process).
        self.add = jax.jit(self.add)
        self.double = jax.jit(self.double)
        self.scalar_mul_bits = jax.jit(self.scalar_mul_bits)
        self.sum_sequential = jax.jit(
            self.sum_sequential, static_argnames=("axis",)
        )

    def _triple_int(self, b):
        p = self.F.p if hasattr(self.F, "p") else self.F.fq.p
        if isinstance(b, tuple):
            return tuple(3 * c % p for c in b)
        return 3 * b % p

    def _const(self, v):
        return self.F.encode([v])[0]

    # -- construction / conversion -------------------------------------------

    def encode(self, points) -> jnp.ndarray:
        """List of affine (x, y) tuples / None (infinity) -> device array.

        For G2, coordinates are themselves (c0, c1) pairs.
        """
        flat = []
        for p in points:
            if p is None:
                if self.coord_axes == 1:
                    flat.append((0, 1, 0))
                else:
                    flat.append(((0, 0), (1, 0), (0, 0)))
            else:
                x, y = p
                if self.coord_axes == 1:
                    flat.append((x, y, 1))
                else:
                    flat.append((x, y, (1, 0)))
        return self.F.encode(flat)

    def decode(self, pts):
        """Device projective points -> list of affine int tuples / None."""
        arr = self.F.decode(pts)
        arr = np.asarray(arr, dtype=object)
        batch = arr.shape[: arr.ndim - 1 - (self.coord_axes - 1)]
        # arr has shape batch + (3,) [+ (2,)]
        flat = arr.reshape((-1, 3) + ((2,) if self.coord_axes == 2 else ()))
        out = []
        from .refmath import finv

        # the curve's OWN base modulus (refmath's fq2_* are BN254-bound, so
        # the Fq2 normalization below is done locally mod p_mod — decoding
        # a BLS12-381 G2 point through BN254 ops silently garbled it)
        p_mod = self.F.p if hasattr(self.F, "p") else self.F.fq.p
        from .primemath import fq2_inv as f2inv, fq2_mul as f2mul

        for row in flat:
            if self.coord_axes == 1:
                x, y, z = int(row[0]), int(row[1]), int(row[2])
                if z == 0:
                    out.append(None)
                else:
                    zi = finv(z, p_mod)
                    out.append((x * zi % p_mod, y * zi % p_mod))
            else:
                x = (int(row[0][0]), int(row[0][1]))
                y = (int(row[1][0]), int(row[1][1]))
                z = (int(row[2][0]), int(row[2][1]))
                if z == (0, 0):
                    out.append(None)
                else:
                    zi = f2inv(z, p_mod)
                    out.append((f2mul(x, zi, p_mod), f2mul(y, zi, p_mod)))
        if batch == ():
            return out[0]
        if len(batch) == 1:
            return out
        obj = np.empty(len(out), dtype=object)
        for i, v in enumerate(out):
            obj[i] = v
        return obj.reshape(batch).tolist()

    def infinity(self, shape=()):
        """(0 : 1 : 0) broadcast to the given batch shape."""
        z = jnp.broadcast_to(self._zero_c, shape + (1,) + self.elem_shape)
        o = jnp.broadcast_to(self._one_c, shape + (1,) + self.elem_shape)
        return jnp.concatenate([z, o, z], axis=-1 - self.coord_axes)

    def _coords(self, p):
        ax = -1 - self.coord_axes
        x = jnp.take(p, 0, axis=ax)
        y = jnp.take(p, 1, axis=ax)
        z = jnp.take(p, 2, axis=ax)
        return x, y, z

    def _pack(self, x, y, z):
        return jnp.stack([x, y, z], axis=-1 - self.coord_axes)

    def is_infinity(self, p):
        _, _, z = self._coords(p)
        if self.coord_axes == 1:
            return jnp.all(z == 0, axis=-1)
        return jnp.all(z == 0, axis=(-1, -2))

    # -- group law (complete, branchless) ------------------------------------

    def _mul_many(self, lhs, rhs):
        """Stacked field muls: one mul call over a new leading axis.

        Independent products inside the group-law formulas are batched into a
        single Montgomery multiply so the compiled graph holds one CIOS loop
        per *round* of the formula instead of one per product — ~4x smaller
        graphs and better VPU utilization at small batch sizes.
        """
        shape = jnp.broadcast_shapes(*(x.shape for x in lhs), *(x.shape for x in rhs))
        lhs = [jnp.broadcast_to(x, shape) for x in lhs]
        rhs = [jnp.broadcast_to(x, shape) for x in rhs]
        return self.F.mul(jnp.stack(lhs, axis=0), jnp.stack(rhs, axis=0))

    def add(self, p, q):
        """Complete projective addition (RCB16 algorithm 7, a = 0),
        regrouped into 3 stacked multiply rounds."""
        F = self.F
        X1, Y1, Z1 = self._coords(p)
        X2, Y2, Z2 = self._coords(q)
        # round 1: all products of input coordinates
        r1 = self._mul_many(
            [X1, Y1, Z1, F.add(X1, Y1), F.add(Y1, Z1), F.add(X1, Z1)],
            [X2, Y2, Z2, F.add(X2, Y2), F.add(Y2, Z2), F.add(X2, Z2)],
        )
        t0, t1, t2 = r1[0], r1[1], r1[2]
        t3 = F.sub(r1[3], F.add(t0, t1))  # X1Y2 + X2Y1
        t4 = F.sub(r1[4], F.add(t1, t2))  # Y1Z2 + Y2Z1
        ty = F.sub(r1[5], F.add(t0, t2))  # X1Z2 + X2Z1
        t0 = F.add(F.add(t0, t0), t0)  # 3 X1X2
        # round 2: multiplications by the constant b3
        r2 = self._mul_many([t2, ty], [self.b3, self.b3])
        t2b, yb = r2[0], r2[1]
        Z3 = F.add(t1, t2b)
        t1 = F.sub(t1, t2b)
        # round 3: the six cross products forming the output coordinates
        r3 = self._mul_many(
            [t3, t4, yb, t1, t0, Z3], [t1, yb, t0, Z3, t3, t4]
        )
        X3 = F.sub(r3[0], r3[1])
        Y3 = F.add(r3[2], r3[3])
        Z3 = F.add(r3[5], r3[4])
        return self._pack(X3, Y3, Z3)

    def double(self, p):
        """Complete projective doubling (RCB16 algorithm 9, a = 0),
        regrouped into 3 stacked multiply rounds."""
        F = self.F
        X, Y, Z = self._coords(p)
        r1 = self._mul_many([Y, Y, Z, X], [Y, Z, Z, Y])
        t0, t1, t2, txy = r1[0], r1[1], r1[2], r1[3]
        z8 = F.add(t0, t0)
        z8 = F.add(z8, z8)
        z8 = F.add(z8, z8)  # 8 Y^2
        (t2b,) = self._mul_many([t2], [self.b3])
        y3a = F.add(t0, t2b)
        t0 = F.sub(t0, F.add(F.add(t2b, t2b), t2b))  # Y^2 - 3 b3 Z^2
        r3 = self._mul_many([t2b, t1, t0, t0], [z8, z8, y3a, txy])
        X3g, Z3, Y3m, X3m = r3[0], r3[1], r3[2], r3[3]
        Y3 = F.add(X3g, Y3m)
        X3 = F.add(X3m, X3m)
        return self._pack(X3, Y3, Z3)

    def neg(self, p):
        X, Y, Z = self._coords(p)
        return self._pack(X, self.F.neg(Y), Z)

    def endo(self, p):
        """The GLV endomorphism phi(X:Y:Z) = (beta*X : Y : Z) with
        phi(P) = lambda*P (ops/glv.py). Only for curves with `glv` set."""
        X, Y, Z = self._coords(p)
        return self._pack(self.F.mul(X, self._beta_c), Y, Z)

    def select(self, cond, p, q):
        """where(cond, p, q) with cond of batch shape."""
        c = cond
        for _ in range(self.coord_axes + 1):
            c = c[..., None]
        return jnp.where(c, p, q)

    # -- derived ops ----------------------------------------------------------

    def scalar_mul_bits(self, p, bits):
        """p * k with k given as a (..., nbits) uint32 bit array (LSB first),
        batch-broadcastable against p's batch shape. Double-and-add, fixed
        trip count — one compiled program for any scalar."""
        nbits = bits.shape[-1]
        acc = self.infinity(p.shape[: -1 - self.coord_axes])
        acc = jnp.broadcast_to(
            acc,
            jnp.broadcast_shapes(p.shape[: -1 - self.coord_axes], bits.shape[:-1])
            + (3,)
            + self.elem_shape,
        )
        p = jnp.broadcast_to(p, acc.shape)

        def body(i, state):
            acc, base = state
            bit = bits[..., i]
            acc = self.select(bit == 1, self.add(acc, base), acc)
            return acc, self.double(base)

        acc, _ = jax.lax.fori_loop(0, nbits, body, (acc, p))
        return acc

    def sum(self, pts, axis=0):
        """Tree-reduce point sum along a batch axis (log n add rounds)."""
        ax = axis % (pts.ndim - 1 - self.coord_axes)
        n = pts.shape[ax]
        pts = jnp.moveaxis(pts, ax, 0)
        while n > 1:
            half = n // 2
            lo = pts[: half]
            hi = pts[half : 2 * half]
            s = self.add(lo, hi)
            if n % 2:
                s = jnp.concatenate([s, pts[2 * half :][:1]], axis=0)
            pts = s
            n = pts.shape[0]
        return pts[0]

    def sum_sequential(self, pts, axis=0):
        """Point sum along an axis via fori_loop accumulation — ONE add
        instantiation versus the tree's log n. Each distinct add/double
        instance costs seconds of XLA:CPU compile (the mesh-prover dryrun
        blowup of VERDICT r2 weak #3), so small-n reductions inside large
        traced programs should prefer this; large-n hot-path reductions
        keep the parallel tree of `sum`."""
        ax = axis % (pts.ndim - 1 - self.coord_axes)
        pts = jnp.moveaxis(pts, ax, 0)
        n = pts.shape[0]
        acc0 = jnp.broadcast_to(self.infinity(), pts.shape[1:])

        def body(i, acc):
            return self.add(acc, pts[i])

        return jax.lax.fori_loop(0, n, body, acc0)

    def to_affine(self, pts):
        """Projective -> affine (x, y) coords on device; infinity -> (0, 0).

        Returns (..., 2) + elem_shape. One batched (Montgomery-trick) field
        inversion over the flattened batch: ~3n muls + one Fermat exp.
        """
        X, Y, Z = self._coords(pts)
        batch = Z.shape[: Z.ndim - self.coord_axes]
        nl = self.elem_shape[-1]  # limb count is field-dependent (BN254=16,
        # BLS12-377=24); hard-coding N_LIMBS here silently garbled any
        # non-16-limb curve's coordinates
        if self.coord_axes == 1:
            zinv = self.F.batch_inv(Z.reshape((-1, nl))).reshape(Z.shape)
        else:
            # Fq2 batch inverse via the norm map: 1/(a0+a1 u) =
            # (a0 - a1 u) / (a0^2 + a1^2), with the Fq norms batch-inverted.
            f = self.F.fq
            a0 = Z[..., 0, :].reshape((-1, nl))
            a1 = Z[..., 1, :].reshape((-1, nl))
            norm = f.add(f.sqr(a0), f.sqr(a1))
            ninv = f.batch_inv(norm)
            zinv = jnp.stack(
                [f.mul(a0, ninv), f.neg(f.mul(a1, ninv))], axis=-2
            ).reshape(batch + (2, nl))
        x = self.F.mul(X, zinv)
        y = self.F.mul(Y, zinv)
        return jnp.stack([x, y], axis=-1 - self.coord_axes)

    def from_affine(self, aff, inf_mask=None):
        """(..., 2)+elem affine coords (+ optional infinity mask) -> projective."""
        ax = -1 - self.coord_axes
        x = jnp.take(aff, 0, axis=ax)
        y = jnp.take(aff, 1, axis=ax)
        one = jnp.broadcast_to(self._one_c, x.shape)
        p = self._pack(x, y, one)
        if inf_mask is not None:
            p = self.select(inf_mask, self.infinity(x.shape[: ax + 1 or None]), p)
        return p

    def is_on_curve(self, p):
        """Projective on-curve check: Y^2 Z == X^3 + b Z^3 (vacuous at inf)."""
        F = self.F
        X, Y, Z = self._coords(p)
        lhs = F.mul(F.mul(Y, Y), Z)
        z3 = F.mul(F.mul(Z, Z), Z)
        rhs = F.add(F.mul(F.mul(X, X), X), F.mul(self.b, z3))
        return F.eq(lhs, rhs)

    def eq(self, p, q):
        """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
        F = self.F
        X1, Y1, Z1 = self._coords(p)
        X2, Y2, Z2 = self._coords(q)
        ex = F.eq(F.mul(X1, Z2), F.mul(X2, Z1))
        ey = F.eq(F.mul(Y1, Z2), F.mul(Y2, Z1))
        i1, i2 = self.is_infinity(p), self.is_infinity(q)
        both_inf = jnp.logical_and(i1, i2)
        one_inf = jnp.logical_xor(i1, i2)
        return jnp.logical_or(both_inf, jnp.logical_and(ex & ey, ~one_inf))


@functools.cache
def g1() -> CurvePoints:
    from .glv import bn254_g1_glv

    return CurvePoints(fq(), G1_B, (N_LIMBS,), glv=bn254_g1_glv())


@functools.cache
def g2() -> CurvePoints:
    return CurvePoints(fq2(), G2_B, (2, N_LIMBS))


def fixed_scalar_ladder_tensors(curve: CurvePoints, scalars):
    """Ladder tensors for a flat list of FIXED Fr scalars: (bits, signs, nbits).

    The shared precomputation of every fixed-scalar point transform
    (parallel/pss.py dense matrices, parallel/pointntt.py twiddles). Under
    GLV (curve.glv set) each scalar splits into two signed ~129-bit halves
    applied to {P, phi(P)}: bits (2, S, nbits) uint32, signs (2, S) bool,
    part 0 = k1 on P, part 1 = k2 on phi(P). Without GLV: bits
    (1, S, nbits=256), signs None.
    """
    from .constants import to_limbs

    def raw_limbs(vals):
        # NOT encode_scalars_std: that reduces mod BN254 Fr, which silently
        # corrupts scalars of a larger-order curve (r381 is 255-bit). The
        # values here are already reduced mod curve.r.
        return jnp.asarray(
            np.array([to_limbs(v) for v in vals], dtype=np.uint32)
        )

    s = [v % curve.r for v in scalars]
    n = len(s)
    if curve.glv is not None:
        nbits = curve.glv.max_bits
        halves = [curve.glv.decompose(v) for v in s]
        flat = [abs(h[p]) for p in (0, 1) for h in halves]
        sgn = [h[p] < 0 for p in (0, 1) for h in halves]
        bits = scalar_bits(raw_limbs(flat), nbits).reshape(2, n, nbits)
        signs = jnp.asarray(np.array(sgn, dtype=bool).reshape(2, n))
        return bits, signs, nbits
    bits = scalar_bits(raw_limbs(s), 256).reshape(1, n, 256)
    return bits, None, 256


def scalar_bits(scalars, nbits: int = 256) -> jnp.ndarray:
    """Standard-form scalar limb array (..., 16) -> bit array (..., nbits).

    Scalars must be in standard (non-Montgomery) form; the decomposition is
    pure limb shifting, independent of any field.
    """
    from .constants import LIMB_BITS

    limb = scalars[..., jnp.arange(nbits) // LIMB_BITS]
    return (limb >> (jnp.arange(nbits) % LIMB_BITS)) & 1
