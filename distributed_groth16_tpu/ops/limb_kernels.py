"""Limb-major Pallas TPU kernels for BN254 field + G1 arithmetic.

This is the TPU fast path for the prover's dominant kernel, the MSM (the
reference's per-party hot loop is arkworks `G::msm`,
dist-primitives/src/dmsm/mod.rs:82). The row-major (..., 16)-limb layout of
ops/field.py is right for host interop and XLA composition, but its per-op
`moveaxis` transposes and tiny carry scans cap batched curve adds at a few
M adds/s. Here field elements live **limb-major** — uint32 arrays of shape
(16, n): limb index on the sublane axis, batch on the lane axis — so every
field op is a dense (16, n) vector op with no transposes, and whole group-law
formulas (RCB16 complete add/double) compile to single Pallas kernels that
keep all intermediates in VMEM.

Representation: Montgomery form, *redundant* residues in [0, 2p). The
Montgomery product of inputs < 2p is < 2p (since 4p < 2^256), so `mul` is
closed with no conditional subtract; add/sub do one conditional -2p. Values
are canonicalised (single conditional -p) only at the boundary back to the
row-major world.

Everything here is generic over the modulus via `LimbField`, instantiated
for BN254 Fq; the same machinery can host BLS12-381's base field.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import config as _config
from .constants import LIMB_BITS, N_LIMBS, Q, to_limbs

MASK = 0xFFFF
NL = N_LIMBS

# Pallas lane-axis tile; 2048 measured fastest for the fused add kernel on
# v5e (1024 and 4096 are both ~25% slower; 8192 exceeds scoped VMEM).
TILE = 2048


def _pallas_roll_mode() -> str:
    """How Pallas kernel bodies are built — a compile-time/runtime tradeoff.

    'unroll': trace-time flat bodies (~6k vector ops per group-law kernel).
        Fastest steady state, but with ~30 kernel instances per MSM program
        the remote Mosaic compile of the monolithic tree at 2^16 ran 40+
        minutes without completing (2026-07-31, v5e tunnel).
    'fori':   CIOS rounds + carry chains as lax.fori_loop with
        concat-rotate row access (carry a rotated copy, read row 0 by
        STATIC slice — dynamic_slice and lax.scan xs-slicing both fail
        Mosaic lowering here, and masked iota-reduction extraction costs
        ~4 full-tile ops per access) — ~4x smaller StableHLO than
        'unroll' (2^14 tree program: 1.2 MB vs 4.7 MB).
    'scan':   the unroll=False lax.scan formulation. DOES NOT LOWER in
        this jax's Mosaic (_scan_lowering_rule raises NotImplementedError
        for extensive inputs/outputs) — kept only as documentation of the
        measurement; selecting it fails at first kernel trace.

    All formulations are bit-identical on the XLA fallback
    (tests/test_limb_roll.py).

    The env var is read ONCE at module import: the chosen mode is baked
    into process-global caches (_SmallNTT cached properties,
    LimbGroup._horner functools.cache, jit caches), so a mid-process env
    change could not take effect anyway — capturing at import makes the
    knob honestly process-start-only (tpu_session.sh already launches a
    fresh process per mode).
    """
    return _ROLL_MODE


_ROLL_MODE = _config.env_str("DG16_PALLAS_ROLL", "fori")


def kernel_roll_mode():
    """unroll arg for Pallas kernel bodies, from DG16_PALLAS_ROLL."""
    m = _pallas_roll_mode()
    return True if m == "unroll" else (False if m == "scan" else "fori")


def _pl():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl, pltpu


def use_pallas() -> bool:
    """Pallas path only on a real TPU backend; elsewhere the same body
    functions run as plain XLA (bit-identical math)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Field bodies (pure jnp, limb-major (16, n); trace-time unrolled)
# ---------------------------------------------------------------------------


def _rot(a):
    """Rotate rows up by one: row 0 moves to the bottom. Static slices +
    concat only — both lower in Mosaic (dynamic_slice and masked
    iota-reduction extraction do not / cost ~4 full-tile ops per access).
    fori bodies carry a rotated copy and always read row 0."""
    return jnp.concatenate([a[1:], a[0:1]], axis=0)


class LimbField:
    """Montgomery arithmetic on limb-major uint32[nl, n] in [0, 2p).

    nl defaults to 16 rows (BN254-class, radix 2^256); larger moduli pass
    their limb count (24 for BLS12-377/381 Fq, radix 2^384) and every
    body below derives its row count from self.nl / the input shape —
    same ops, same roll modes, wider tiles."""

    def __init__(self, modulus: int, nl: int = NL):
        assert 4 * modulus < 1 << (LIMB_BITS * nl), "lazy-carry redundancy"
        self.p = modulus
        self.nl = nl
        self.CR = nl  # coordinate rows: one Fq element = nl limb rows
        self.n0 = int((-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        self.p_col = np.array(to_limbs(modulus, nl), np.uint32).reshape(nl, 1)
        self.p2_col = np.array(
            to_limbs(2 * modulus, nl), np.uint32
        ).reshape(nl, 1)
        self.mont_r = (1 << (LIMB_BITS * nl)) % modulus

    # consts are passed in explicitly so the same bodies work inside Pallas
    # kernels (which reject captured device constants).

    # Each helper has THREE formulations with IDENTICAL op sequences (hence
    # identical numerics), selected by `unroll`: True = trace-time unrolled
    # (flat bodies — fastest steady state, but the compile cost of ~30 such
    # kernel instances wedged the remote Mosaic service for 40+ min on the
    # 2^16 tree program); False = `lax.scan`-rolled for the plain-XLA
    # fallback (unrolled 3k-op graphs made CPU test compiles minutes-long);
    # "fori" = `lax.fori_loop`-rolled with concat-rotate row access, the
    # Pallas compile-friendly middle ground (~10x smaller bodies).

    def carry(self, v, unroll=True):
        """(k, n) lazy rows -> (nl, n) carried limbs (value < radix).

        Rows beyond nl (the CIOS accumulator's top row, zero by the shift
        invariant) are dropped.
        """
        nl = self.nl
        v = v[:nl]
        if unroll == "fori":
            # out self-assembles by appending each carried row at the
            # bottom: after nl iterations rows sit in order 0..nl-1.
            def body(i, st):
                out, c, vr = st
                t = vr[0:1] + c
                return (
                    jnp.concatenate([out[1:], t & MASK], axis=0),
                    t >> LIMB_BITS,
                    _rot(vr),
                )

            out, _, _ = jax.lax.fori_loop(
                0, nl, body,
                (jnp.zeros_like(v), jnp.zeros_like(v[0:1]), v),
            )
            return out
        if not unroll:
            def step(c, row):
                t = row + c
                return t >> LIMB_BITS, t & MASK

            _, out = jax.lax.scan(step, jnp.zeros_like(v[0]), v)
            return out
        rows, c = [], jnp.zeros_like(v[0:1])
        for i in range(nl):
            t = v[i : i + 1] + c
            rows.append(t & MASK)
            c = t >> LIMB_BITS
        return jnp.concatenate(rows, axis=0)

    @staticmethod
    def _cond_sub(a, m_col, unroll=True):
        """a - m if a >= m else a; a carried, m a (nl,1) numpy/jnp column
        (row count derived from a — shared by every limb width)."""
        nl = a.shape[0]
        if unroll == "fori":
            m_col = jnp.asarray(m_col)

            def body(i, st):
                d, b, ar, mr = st
                t = ar[0:1] - mr[0:1] - b
                return (
                    jnp.concatenate([d[1:], t & MASK], axis=0),
                    t >> 31,
                    _rot(ar),
                    _rot(mr),
                )

            d, b, _, _ = jax.lax.fori_loop(
                0, nl, body,
                (jnp.zeros_like(a), jnp.zeros_like(a[0:1]), a, m_col),
            )
            return jnp.where(b == 0, d, a)
        if not unroll:
            def step(b, xs):
                ai, mi = xs
                t = ai - mi - b
                return t >> 31, t & MASK

            b, d = jax.lax.scan(
                step, jnp.zeros_like(a[0]), (a, m_col * jnp.ones_like(a))
            )
            return jnp.where(b == 0, d, a)
        rows, b = [], jnp.zeros_like(a[0:1])
        for i in range(nl):
            t = a[i : i + 1] - m_col[i] - b
            rows.append(t & MASK)
            b = t >> 31
        d = jnp.concatenate(rows, axis=0)
        return jnp.where(b == 0, d, a)

    def add(self, a, b, p2, unroll=True):
        """(a + b) mod* : inputs < 2p -> output < 2p."""
        return self._cond_sub(self.carry(a + b, unroll), p2, unroll)

    def neg(self, b, p2, unroll=True):
        """2p - b (the additive inverse in the redundant class), b < 2p."""
        if unroll == "fori":
            p2 = jnp.asarray(p2)

            def body(i, st):
                out, brw, br, pr = st
                t = pr[0:1] - br[0:1] - brw
                return (
                    jnp.concatenate([out[1:], t & MASK], axis=0),
                    t >> 31,
                    _rot(br),
                    _rot(pr),
                )

            out, _, _, _ = jax.lax.fori_loop(
                0, b.shape[0], body,
                (jnp.zeros_like(b), jnp.zeros_like(b[0:1]), b, p2),
            )
            return out
        if not unroll:
            def step(brw, xs):
                bi, pi = xs
                t = pi - bi - brw
                return t >> 31, t & MASK

            _, out = jax.lax.scan(
                step, jnp.zeros_like(b[0]), (b, p2 * jnp.ones_like(b))
            )
            return out
        rows, brw = [], jnp.zeros_like(b[0:1])
        for i in range(b.shape[0]):
            t = p2[i] - b[i : i + 1] - brw
            rows.append(t & MASK)
            brw = t >> 31
        return jnp.concatenate(rows, axis=0)

    def sub(self, a, b, p2, unroll=True):
        return self._cond_sub(
            self.carry(a + self.neg(b, p2, unroll), unroll), p2, unroll
        )

    def mul(self, a, b, p, unroll=True):
        """Montgomery product, CIOS with lazy carries; inputs < 2p (limbs
        <= 0xffff) -> output < 2p. nl rounds of dense (nl, n) ops, one
        final carry chain, no conditional subtract."""
        nl = self.nl
        n = a.shape[-1]
        z1 = jnp.zeros((1, n), jnp.uint32)

        def step(v, ai):
            prod = ai * b  # (nl, n); both operands <= 0xffff
            # rows 1..nl-1 receive lo[1:] + hi[:-1]: merge before widening
            mid = (prod[1:] & MASK) + (prod[:-1] >> LIMB_BITS)
            contrib = jnp.concatenate(
                [prod[0:1] & MASK, mid, prod[nl - 1 : nl] >> LIMB_BITS],
                axis=0,
            )
            v = v + contrib
            m = (v[0:1] * self.n0) & MASK
            qp = m * p
            qmid = (qp[1:] & MASK) + (qp[:-1] >> LIMB_BITS)
            qcontrib = jnp.concatenate(
                [qp[0:1] & MASK, qmid, qp[nl - 1 : nl] >> LIMB_BITS],
                axis=0,
            )
            v = v + qcontrib
            return jnp.concatenate(
                [v[1:2] + (v[0:1] >> LIMB_BITS), v[2:], z1], axis=0
            )

        v0 = jnp.zeros((nl + 1, n), jnp.uint32)
        if unroll == "fori":
            def body(i, st):
                v, ar = st
                return step(v, ar[0:1]), _rot(ar)

            v, _ = jax.lax.fori_loop(0, nl, body, (v0, a))
            return self.carry(v, unroll="fori")
        if not unroll:
            v, _ = jax.lax.scan(
                lambda v, ai: (step(v, ai[None]), None), v0, a[:nl]
            )
            return self.carry(v, unroll=False)
        v = v0
        for i in range(nl):
            v = step(v, a[i : i + 1])
        return self.carry(v)

    def canon(self, a):
        """[0, 2p) carried -> canonical [0, p)."""
        return self._cond_sub(a, jnp.asarray(self.p_col))

    # -- group-law plumbing --------------------------------------------------

    def make_ops(self, p, p2, unroll=True):
        """(mul, add, sub) closures over the consts blocks — the interface
        the group-law bodies are written against, shared with LimbFq2."""
        return (
            lambda x, y: self.mul(x, y, p, unroll),
            lambda x, y: self.add(x, y, p2, unroll),
            lambda x, y: self.sub(x, y, p2, unroll),
        )

    def neg_rows(self, a, p2, unroll=True):
        return self.neg(a, p2, unroll)

    def canon_rows(self, a):
        return self.canon(a)

    def b3_limbs(self, b) -> np.ndarray:
        """3*b Montgomery-encoded as a (nl, 1) limb column."""
        v = 3 * b * self.mont_r % self.p
        return np.array(to_limbs(v, self.nl), np.uint32).reshape(self.nl, 1)

    def one_limbs(self) -> np.ndarray:
        return np.array(to_limbs(self.mont_r, self.nl), np.uint32)


class LimbFq2:
    """Fq2 = Fq[u]/(u^2 + 1) on limb-major uint32[2*nl, n]: rows 0..nl-1
    c0, nl..2nl-1 c1. Karatsuba over LimbField's redundant-[0, 2p)
    Montgomery arithmetic — all component ops stay closed in [0, 2p)."""

    def __init__(self, base: LimbField):
        self.fq = base
        self.nl = base.nl
        self.CR = 2 * base.nl
        self.p = base.p
        self.p_col = base.p_col
        self.p2_col = base.p2_col
        self.mont_r = base.mont_r

    def make_ops(self, p, p2, unroll=True):
        F = self.fq
        nl = self.nl

        def mul(a, b):
            a0, a1 = a[0:nl], a[nl:]
            b0, b1 = b[0:nl], b[nl:]
            t0 = F.mul(a0, b0, p, unroll)
            t1 = F.mul(a1, b1, p, unroll)
            c0 = F.sub(t0, t1, p2, unroll)  # u^2 = -1
            sa = F.add(a0, a1, p2, unroll)
            sb = F.add(b0, b1, p2, unroll)
            c1 = F.sub(
                F.mul(sa, sb, p, unroll), F.add(t0, t1, p2, unroll),
                p2, unroll,
            )
            return jnp.concatenate([c0, c1], axis=0)

        def add(a, b):
            return jnp.concatenate(
                [
                    F.add(a[0:nl], b[0:nl], p2, unroll),
                    F.add(a[nl:], b[nl:], p2, unroll),
                ],
                axis=0,
            )

        def sub(a, b):
            return jnp.concatenate(
                [
                    F.sub(a[0:nl], b[0:nl], p2, unroll),
                    F.sub(a[nl:], b[nl:], p2, unroll),
                ],
                axis=0,
            )

        return mul, add, sub

    def neg_rows(self, a, p2, unroll=True):
        F, nl = self.fq, self.nl
        return jnp.concatenate(
            [F.neg(a[0:nl], p2, unroll), F.neg(a[nl:], p2, unroll)], axis=0
        )

    def canon_rows(self, a):
        F, nl = self.fq, self.nl
        return jnp.concatenate([F.canon(a[0:nl]), F.canon(a[nl:])], axis=0)

    def b3_limbs(self, b) -> np.ndarray:
        """3*b' Montgomery-encoded as a (2*nl, 1) limb column (b' in Fq2)."""
        b0, b1 = b
        nl = self.nl
        return np.concatenate(
            [
                np.array(
                    to_limbs(3 * b0 * self.mont_r % self.p, nl), np.uint32
                ).reshape(nl, 1),
                np.array(
                    to_limbs(3 * b1 * self.mont_r % self.p, nl), np.uint32
                ).reshape(nl, 1),
            ],
            axis=0,
        )

    def one_limbs(self) -> np.ndarray:
        one = np.zeros((2 * self.nl,), np.uint32)
        one[: self.nl] = np.array(
            to_limbs(self.mont_r, self.nl), np.uint32
        )
        return one


@functools.cache
def lfq() -> LimbField:
    return LimbField(Q)


@functools.cache
def lfq2() -> LimbFq2:
    return LimbFq2(lfq())


# ---------------------------------------------------------------------------
# Group law bodies on limb-major points (3*CR, n): X rows then Y then Z
# (projective, RCB16 complete formulas, a = 0). CR = 16 (G1/Fq) or 32
# (G2/Fq2): the SAME formula code serves both via the field's make_ops.
# ---------------------------------------------------------------------------


class LimbGroup:
    """A short-Weierstrass group (a = 0) on limb-major uint32[3*CR, n]."""

    def __init__(self, field, b, tile: int | None = None):
        self.F = field
        self.CR = field.CR
        self.ROWS = 3 * self.CR
        # base-field limb rows (== CR for Fq, CR/2 for Fq2) — the consts
        # block and kernel bodies slice by this, not a hardcoded 16
        self.base_nl = field.p_col.shape[0]
        # Pallas lane tile: scaled down as rows grow (VMEM budget is
        # rows x tile), floored to a power of two
        if tile is None:
            tile = max(256, TILE * (3 * NL) // self.ROWS)
            tile = 1 << (tile.bit_length() - 1)
        self.tile = tile
        # consts block handed to every kernel:
        # rows [0:bn] p, [bn:2bn] 2p, [2bn:2bn+CR] b3 (Montgomery)
        self.consts_np = np.concatenate(
            [field.p_col, field.p2_col, field.b3_limbs(b)], axis=0
        )
        inf = np.zeros((self.ROWS,), np.uint32)
        inf[self.CR : self.CR + field.one_limbs().shape[0]] = (
            field.one_limbs()
        )
        self.inf_col = inf.reshape(self.ROWS, 1)

    # -- bodies -------------------------------------------------------------

    def add_body(self, p3, q3, consts, unroll=True):
        CR, bn = self.CR, self.base_nl
        p, p2, b3c = consts[0:bn], consts[bn : 2 * bn], consts[2 * bn :]
        mul, add, sub = self.F.make_ops(p, p2, unroll)
        X1, Y1, Z1 = p3[0:CR], p3[CR : 2 * CR], p3[2 * CR :]
        X2, Y2, Z2 = q3[0:CR], q3[CR : 2 * CR], q3[2 * CR :]
        t0 = mul(X1, X2)
        t1 = mul(Y1, Y2)
        t2 = mul(Z1, Z2)
        t3 = sub(mul(add(X1, Y1), add(X2, Y2)), add(t0, t1))
        t4 = sub(mul(add(Y1, Z1), add(Y2, Z2)), add(t1, t2))
        ty = sub(mul(add(X1, Z1), add(X2, Z2)), add(t0, t2))
        t0_3 = add(add(t0, t0), t0)
        t2b = mul(t2, b3c)
        yb = mul(ty, b3c)
        Z3 = add(t1, t2b)
        t1m = sub(t1, t2b)
        X3 = sub(mul(t3, t1m), mul(t4, yb))
        Y3 = add(mul(yb, t0_3), mul(t1m, Z3))
        Z3o = add(mul(Z3, t4), mul(t0_3, t3))
        return jnp.concatenate([X3, Y3, Z3o], axis=0)

    def double_body(self, p3, consts, unroll=True):
        CR, bn = self.CR, self.base_nl
        p, p2, b3c = consts[0:bn], consts[bn : 2 * bn], consts[2 * bn :]
        mul, add, sub = self.F.make_ops(p, p2, unroll)
        X, Y, Z = p3[0:CR], p3[CR : 2 * CR], p3[2 * CR :]
        t0 = mul(Y, Y)
        t1 = mul(Y, Z)
        t2 = mul(Z, Z)
        txy = mul(X, Y)
        z8 = add(t0, t0)
        z8 = add(z8, z8)
        z8 = add(z8, z8)  # 8 Y^2
        t2b = mul(t2, b3c)
        y3a = add(t0, t2b)
        t0m = sub(t0, add(add(t2b, t2b), t2b))
        X3g = mul(t2b, z8)
        Z3 = mul(t1, z8)
        Y3m = mul(t0m, y3a)
        X3m = mul(t0m, txy)
        Y3 = add(X3g, Y3m)
        X3 = add(X3m, X3m)
        return jnp.concatenate([X3, Y3, Z3], axis=0)

    def neg_body(self, p3, consts):
        CR, bn = self.CR, self.base_nl
        p2 = consts[bn : 2 * bn]
        return jnp.concatenate(
            [
                p3[0:CR],
                self.F.neg_rows(p3[CR : 2 * CR], p2),
                p3[2 * CR :],
            ],
            axis=0,
        )

    # -- pallas / XLA dispatch ---------------------------------------------

    _kmode = staticmethod(kernel_roll_mode)

    def _consts(self):
        return jnp.asarray(self.consts_np)

    @functools.cached_property
    def _xla_add(self):
        return jax.jit(
            lambda p, q: self.add_body(p, q, self._consts(), unroll=False)
        )

    @functools.cached_property
    def _xla_double(self):
        return jax.jit(
            lambda p: self.double_body(p, self._consts(), unroll=False)
        )

    @functools.cached_property
    def _pallas_add(self):
        pl, pltpu = _pl()
        RR, T, CROWS = self.ROWS, self.tile, self.consts_np.shape[0]

        def kern(p_ref, q_ref, c_ref, o_ref):
            o_ref[:] = self.add_body(
                p_ref[:], q_ref[:], c_ref[:], unroll=self._kmode()
            )

        @jax.jit
        def run(p, q):
            n = p.shape[1]
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((RR, n), jnp.uint32),
                grid=(n // T,),
                in_specs=[
                    pl.BlockSpec((RR, T), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((RR, T), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((CROWS, 1), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((RR, T), lambda i: (0, i),
                                       memory_space=pltpu.VMEM),
            )(p, q, self._consts())

        return run

    @functools.cached_property
    def _pallas_double(self):
        pl, pltpu = _pl()
        RR, T, CROWS = self.ROWS, self.tile, self.consts_np.shape[0]

        def kern(p_ref, c_ref, o_ref):
            o_ref[:] = self.double_body(
                p_ref[:], c_ref[:], unroll=self._kmode()
            )

        @jax.jit
        def run(p):
            n = p.shape[1]
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((RR, n), jnp.uint32),
                grid=(n // T,),
                in_specs=[
                    pl.BlockSpec((RR, T), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((CROWS, 1), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((RR, T), lambda i: (0, i),
                                       memory_space=pltpu.VMEM),
            )(p, self._consts())

        return run

    def _batched(self, fn_pallas, fn_xla, args):
        """Flatten trailing batch axes, pad the lane axis to a power-of-two
        width, run. Power-of-two padding bounds the number of distinct
        compiled shapes (the unrolled group-law graphs are large, so each
        extra shape is a real compile cost on both CPU and TPU)."""
        RR = self.ROWS
        shape = args[0].shape
        flat = [a.reshape(RR, -1) for a in args]
        n = flat[0].shape[1]
        pallas = use_pallas()
        granule = self.tile if pallas else 256
        npad = max(granule, 1 << (n - 1).bit_length())
        if npad != n:
            flat = [jnp.pad(a, ((0, 0), (0, npad - n))) for a in flat]
        out = (fn_pallas if pallas else fn_xla)(*flat)[:, :n]
        return out.reshape(shape)

    def add(self, p, q):
        """Complete add on (ROWS, ...) limb-major batches."""
        q = jnp.broadcast_to(q, p.shape)
        return self._batched(self._pallas_add, self._xla_add, (p, q))

    def double(self, p):
        return self._batched(self._pallas_double, self._xla_double, (p,))

    def neg(self, p):
        return self.neg_body(
            p.reshape(self.ROWS, -1), self._consts()
        ).reshape(p.shape)

    # -- window combine (Horner over c-bit windows), one fused kernel -------

    def horner_body(self, getcol, consts, c: int, W: int, unroll=True):
        """acc = sum_w 2^(c*w) * S_w; getcol(w) -> (ROWS, 1) window sum."""
        RR = self.ROWS
        acc0 = jnp.broadcast_to(getcol(W - 1), (RR, 128))

        def step(i, acc):
            w = W - 2 - i
            for _ in range(c):
                acc = self.double_body(acc, consts, unroll)
            return self.add_body(
                acc, jnp.broadcast_to(getcol(w), (RR, 128)), consts, unroll
            )

        return jax.lax.fori_loop(0, W - 1, step, acc0)

    @functools.cache
    def _horner(self, c: int, W: int):
        RR = self.ROWS
        if not use_pallas():
            return jax.jit(
                lambda s: self.horner_body(
                    lambda w: jax.lax.dynamic_slice(s, (0, w), (RR, 1)),
                    self._consts(), c, W, unroll=False,
                )[:, :1]
            )
        pl, pltpu = _pl()

        def kern(s_ref, c_ref, o_ref):
            s = s_ref[:]
            lane = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)

            def getcol(w):
                # dynamic width-1 lane slices (and unsigned reductions)
                # don't lower in Mosaic; mask + signed lane-reduce does
                masked = jnp.where(lane == w, s, jnp.uint32(0)).astype(
                    jnp.int32
                )
                return jnp.sum(masked, axis=1, keepdims=True).astype(
                    jnp.uint32
                )

            o_ref[:] = self.horner_body(
                getcol, c_ref[:], c, W, unroll=self._kmode()
            )

        @jax.jit
        def run(s):
            out = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((RR, 128), jnp.uint32),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.VMEM),
                    pl.BlockSpec(memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(s, self._consts())
            return out[:, :1]

        return run

    def horner(self, s, c: int):
        """Window sums s (ROWS, W), LSB window first -> one point column."""
        W = s.shape[1]
        if W == 1:
            return s
        return self._horner(c, W)(s)

    # -- layout conversion ---------------------------------------------------

    @property
    def rm_shape(self) -> tuple:
        """Trailing row-major point shape: (3, nl) G1, (3, 2, nl) G2."""
        bn = self.base_nl
        return (3, bn) if self.CR == bn else (3, 2, bn)

    def from_rowmajor(self, pts):
        """(n,) + rm_shape row-major (canonical Montgomery) -> (ROWS, n)."""
        n = pts.shape[0]
        return jnp.transpose(pts.reshape(n, self.ROWS))

    def to_rowmajor(self, lm, canonical: bool = True):
        """(ROWS, n) -> (n,) + rm_shape row-major; canonicalises to [0, p)."""
        if canonical:
            lm = jnp.concatenate(
                [
                    self.F.canon_rows(lm[i * self.CR : (i + 1) * self.CR])
                    for i in range(3)
                ],
                axis=0,
            )
        return jnp.transpose(lm).reshape((-1,) + self.rm_shape)

    def infinity(self, n: int):
        return jnp.broadcast_to(jnp.asarray(self.inf_col), (self.ROWS, n))


# Back-compat name: the original G1-only class was called LimbG1.
LimbG1 = LimbGroup


@functools.cache
def lg1() -> LimbGroup:
    from .constants import G1_B

    return LimbGroup(lfq(), G1_B)


@functools.cache
def lg2() -> LimbGroup:
    from .constants import G2_B

    return LimbGroup(lfq2(), G2_B)


# BLS12-377/381 limb groups: same bodies/kernels at 24 base-field limb
# rows (radix 2^384). The PrimeField configs in ops/bls12_377.py /
# ops/bls12_381.py stay the row-major source of truth; these are the
# Pallas-path mirrors, keyed off the same derived constants.


@functools.cache
def lg1_377() -> LimbGroup:
    from .bls12_377 import G1_B377, Q377, fq377

    return LimbGroup(LimbField(Q377, fq377().nl), G1_B377)


@functools.cache
def lg1_381() -> LimbGroup:
    from .bls12_381 import G1_B381, Q381, fq381

    return LimbGroup(LimbField(Q381, fq381().nl), G1_B381)


@functools.cache
def lg2_381() -> LimbGroup:
    from .bls12_381 import G2_B381, Q381, fq381

    return LimbGroup(LimbFq2(LimbField(Q381, fq381().nl)), G2_B381)


# ---------------------------------------------------------------------------
# Tree MSM: sorted-digit buckets, pairwise sum tree + Fenwick prefix queries
# ---------------------------------------------------------------------------


def _digits(scalars_std, c: int):
    """(n, nl) standard-form u32 limbs -> (W, n) int32 c-bit digits, LSB
    window first, W = nl*16/c. c must divide 16. Width-aware: wider
    scalar layouts (17-limb r381 standard form) just produce more
    (all-zero) top windows — no truncation."""
    assert LIMB_BITS % c == 0
    per = LIMB_BITS // c
    nl_s = scalars_std.shape[1]
    parts = [
        ((scalars_std >> (k * c)) & ((1 << c) - 1)) for k in range(per)
    ]  # each (n, nl)
    inter = jnp.stack(parts, axis=-1).reshape(
        scalars_std.shape[0], nl_s * per
    )
    return jnp.transpose(inter).astype(jnp.int32)  # (W, n)


def msm_tree(points_rm, scalars_std, c: int | None = None,
             window_group: int | None = None, group: "LimbGroup" = None):
    """sum_i scalars[i] * points[i], limb-major TPU path (any LimbGroup).

    points_rm: (n, 3, nl) G1 / (n, 3, 2, nl) G2 projective row-major
    (Montgomery, canonical) — BN254 groups are inferred from the rank
    when `group` is omitted; other curves pass their LimbGroup
    (lg1_377() / lg1_381() / lg2_381());
    scalars_std: (n, k) uint32 standard form (k*16 >= scalar bits).
    Returns the (3, ...) row-major canonical projective sum.

    Per window: points are ordered by digit (argsort), reduced by a pairwise
    sum tree (n-1 adds — vs 2n for an associative_scan — with every level a
    dense Pallas add over all windows at once), and the B-1 bucket prefix
    sums C_j are read off the tree Fenwick-style: C(pos) =
    sum_{d: bit d of pos} level_d[(pos >> d) - 1]. The weighted-bucket
    identity sum_b b*S_b = sum_j (total - C_j) then needs one batched
    neg+add and a small tree sum; windows combine in one fused Horner
    kernel. Matches the role of arkworks G::msm (dmsm/mod.rs:82).

    The whole computation is one jitted program: per-dispatch host latency
    (milliseconds through the remote-TPU tunnel) would otherwise dominate
    the ~30 narrow query/combine steps.
    """
    if c is None:
        # the Fenwick/combine stages scale with B = 2^c per window: a small
        # MSM with c=8 would spend everything on 255 empty buckets
        c = 8 if points_rm.shape[0] >= 4096 else 4
    g = group or (lg2() if points_rm.ndim == 4 else lg1())
    return _msm_tree_jit(g, points_rm, scalars_std, c, window_group)


@functools.partial(jax.jit, static_argnums=(0, 3, 4))
def _msm_tree_jit(g: LimbGroup, points_rm, scalars_std, c: int,
                  window_group: int | None):
    RR = g.ROWS
    n = points_rm.shape[0]
    W_all = scalars_std.shape[1] * LIMB_BITS // c
    B = 1 << c
    npad = 1 << max(1, (n - 1).bit_length())
    lm = g.from_rowmajor(points_rm)
    if npad != n:
        lm = jnp.concatenate([lm, g.infinity(npad - n)], axis=1)
    digits = _digits(scalars_std, c)  # (W, n)
    if npad != n:
        digits = jnp.pad(digits, ((0, 0), (0, npad - n)))
    levels_n = npad.bit_length() - 1  # log2(npad)

    if window_group is None:
        # bound live tree memory to ~8 * 48 * 2^20 * 4 * 2 ≈ 3.2 GB
        # (half the window count for G2's double-width rows)
        window_group = (
            W_all if npad <= (1 << 17) else max(1, 8 * 48 // RR)
        )

    sums = []
    for w0 in range(0, W_all, window_group):
        dg = digits[w0 : w0 + window_group]  # (Wg, npad)
        Wg = dg.shape[0]
        order = jnp.argsort(dg, axis=-1)
        sortd = jnp.take_along_axis(dg, order, axis=-1)
        ends = jax.vmap(
            lambda row: jnp.searchsorted(row, jnp.arange(B - 1), side="right")
        )(sortd)  # (Wg, B-1)
        gathered = jnp.take(lm, order.reshape(-1), axis=1).reshape(RR, Wg, npad)

        # Up-sweep; each level is also kept transposed to (Wg*K, ROWS) so
        # the Fenwick node lookups below are contiguous row gathers
        # (embedding-style) instead of ROWS-way strided minor-axis gathers.
        lvls_t = []
        x = gathered
        lvls_t.append(jnp.transpose(x, (1, 2, 0)).reshape(-1, RR))
        for _ in range(levels_n):
            k = x.shape[-1]
            pair = x.reshape(RR, Wg, k // 2, 2)
            x = g.add(pair[..., 0], pair[..., 1])
            lvls_t.append(jnp.transpose(x, (1, 2, 0)).reshape(-1, RR))
        total = x[..., 0:1]  # (RR, Wg, 1)

        # Fenwick prefix at the B-1 bucket boundaries: gather one node per
        # level per boundary, then sum the levels with a pairwise tree.
        inf_row = jnp.asarray(g.inf_col)[:, 0]  # (RR,)
        nodes = []
        for d in range(levels_n + 1):
            pd = ends >> d
            takebit = (pd & 1) == 1
            idx = jnp.maximum(pd - 1, 0)
            k = npad >> d
            flat = (jnp.arange(Wg)[:, None] * k + idx).reshape(-1)
            node = jnp.take(lvls_t[d], flat, axis=0).reshape(Wg, B - 1, RR)
            node = jnp.where(takebit[..., None], node, inf_row)
            nodes.append(node)
        D = len(nodes)
        dpad = 1 << (D - 1).bit_length()
        stack = jnp.stack(nodes, axis=0)  # (D, Wg, B-1, RR)
        if dpad != D:
            stack = jnp.concatenate(
                [
                    stack,
                    jnp.broadcast_to(inf_row, (dpad - D, Wg, B - 1, RR)),
                ],
                axis=0,
            )
        stack = jnp.transpose(stack, (3, 0, 1, 2))  # (RR, dpad, Wg, B-1)
        while stack.shape[1] > 1:
            half = stack.shape[1] // 2
            stack = g.add(stack[:, :half], stack[:, half:])
        acc = stack[:, 0]  # (RR, Wg, B-1)

        # sum_b b * S_b = sum_{j=0..B-2} (total - C_j)
        terms = g.add(jnp.broadcast_to(total, acc.shape), g.neg(acc))
        k = B - 1
        while k > 1:
            if k % 2:
                terms = jnp.concatenate(
                    [
                        terms,
                        jnp.broadcast_to(
                            jnp.asarray(g.inf_col)[:, :, None], (RR, Wg, 1)
                        ),
                    ],
                    axis=-1,
                )
                k += 1
            pair = terms.reshape(RR, Wg, k // 2, 2)
            terms = g.add(pair[..., 0], pair[..., 1])
            k //= 2
        sums.append(terms[..., 0])  # (RR, Wg)

    s_all = jnp.concatenate(sums, axis=1)  # (RR, W_all)
    out = g.horner(s_all, c)  # (RR, 1)
    return g.to_rowmajor(out)[0]


# ---------------------------------------------------------------------------
# Fixed-scalar ladder application: out[..., o] = sum_k M[o][k] * pts[..., k]
# (the in-the-exponent PSS pack/unpack maps, parallel/pss.py). The ladder
# body is the same batched add/double/select sweep the row-major path runs,
# but on limb-major tensors the adds ride the Pallas kernels.
# ---------------------------------------------------------------------------


def ladder_apply(g: LimbGroup, pts_lm, bits, signs, nbits: int):
    """pts_lm: (ROWS, B, K) limb-major bases (already GLV-expanded when the
    caller uses the endomorphism); bits: (o, K, nbits) uint32; signs:
    (o, K) bool or None. Returns (ROWS, B, o) limb-major points."""
    RR = g.ROWS
    B, K = pts_lm.shape[1], pts_lm.shape[2]
    o = bits.shape[0]
    acc0 = jnp.broadcast_to(
        jnp.asarray(g.inf_col).reshape(RR, 1, 1, 1), (RR, B, o, K)
    )

    def body(i, state):
        acc, base = state
        bit = bits[..., i]  # (o, K)
        addend = base[:, :, None, :]  # (ROWS, B, 1, K)
        if signs is not None:
            # (o, K) broadcasts against (ROWS, B, 1, K) -> (ROWS, B, o, K)
            addend = jnp.where(signs, g.neg(addend), addend)
        cand = g.add(acc, jnp.broadcast_to(addend, acc.shape))
        acc = jnp.where(bit == 1, cand, acc)
        return acc, g.double(base)

    acc, _ = jax.lax.fori_loop(0, nbits, body, (acc0, pts_lm))
    # pairwise tree-sum over the K axis (K is a power of two in practice;
    # pad with infinity otherwise)
    k = K
    x = acc
    while k > 1:
        if k % 2:
            x = jnp.concatenate(
                [x, jnp.broadcast_to(
                    jnp.asarray(g.inf_col).reshape(RR, 1, 1, 1),
                    (RR, B, o, 1))],
                axis=-1,
            )
            k += 1
        pair = x.reshape(RR, B, o, k // 2, 2)
        x = g.add(pair[..., 0], pair[..., 1])
        k //= 2
    return x[..., 0]  # (ROWS, B, o)


# eager fori_loop dispatch is an XLA:CPU crash class in this environment
# (backend_compile_and_load segfault late in a long-lived process): always
# enter the ladder through this jitted wrapper
ladder_apply_jit = jax.jit(ladder_apply, static_argnums=(0, 4))
