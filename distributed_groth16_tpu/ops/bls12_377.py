"""BLS12-377 G1 — the second curve configuration.

The reference benchmarks its distributed kernels over ark-bls12-377
(dist-primitives/examples/dmsm_bench.rs:1,48; groth16/examples/
local_groth_bench.rs), relying on arkworks for the parameters. Here every
constant is DERIVED from the BLS12-377 seed at import and self-checked
(primality, curve membership, subgroup order), so nothing is copied on
trust:

    x  = 0x8508C00000000001                      (the BLS parameter)
    r  = x^4 - x^2 + 1                           (scalar field, 253 bits)
    q  = ((x - 1)^2 * r) / 3 + x                 (base field, 377 bits)
    G1 : y^2 = x^3 + 1 over Fq, cofactor (x-1)^2 / 3

Field elements use 24x16-bit limbs (Montgomery radix 2^384 — the generic
PrimeField of ops/field.py parameterized by limb count); scalars are Fr
elements in the standard 16-limb layout, so the MSM/ladder machinery of
ops/msm.py and ops/curve.py works unchanged. The G1 generator is chosen
deterministically (smallest-x curve point, cofactor-cleared) — generator
choice is a convention, not part of the group.

No pairing/G2 here: kernel-level curve parity (MSM, FFT-in-the-exponent,
PSS) mirrors exactly what the reference exercises over BLS12-377.
"""

from __future__ import annotations

import functools

from . import refmath as rm

# --------------------------------------------------------------------------
# parameter derivation from the seed
# --------------------------------------------------------------------------

X = 0x8508C00000000001
R377 = X**4 - X**2 + 1
Q377 = ((X - 1) ** 2 * R377) // 3 + X
G1_B377 = 1
G1_COFACTOR = (X - 1) ** 2 // 3

FR_TWO_ADICITY_377 = ((R377 - 1) & -(R377 - 1)).bit_length() - 1  # = 47


from .primemath import (
    factor as _factor,
    is_probable_prime as _is_probable_prime,
    smallest_generator,
    sqrt_mod,
)


@functools.cache
def _fr_generator() -> int:
    """Smallest multiplicative generator of Fr377 (arkworks convention).
    r-1 = x^2 (x-1)(x+1) factors through 64-bit integers."""
    return smallest_generator(
        R377, _factor(X) | _factor(X - 1) | _factor(X + 1)
    )


# --------------------------------------------------------------------------
# self-checks (import-time; cheap)
# --------------------------------------------------------------------------

assert R377.bit_length() == 253 and Q377.bit_length() == 377
assert ((X - 1) ** 2 * R377) % 3 == 0, "q derivation divisibility"
assert _is_probable_prime(R377), "r not prime"
assert _is_probable_prime(Q377), "q not prime"
# curve/group consistency: #E(Fq) = h * r = q + 1 - t with t = x + 1
assert G1_COFACTOR * R377 == Q377 + 1 - (X + 1), "Hasse/trace identity"
assert (R377 - 1) % (1 << FR_TWO_ADICITY_377) == 0


# --------------------------------------------------------------------------
# host ground truth
# --------------------------------------------------------------------------

G1_HOST = rm._CurveOps(
    add=lambda a, b: (a + b) % Q377,
    sub=lambda a, b: (a - b) % Q377,
    mul=lambda a, b: a * b % Q377,
    sq=lambda a: a * a % Q377,
    neg=lambda a: (-a) % Q377,
    inv=lambda a: rm.finv(a, Q377),
    scalar=lambda a, k: a * k % Q377,
    zero=0,
    one=1,
    b=G1_B377,
    order=R377,
)


def _sqrt_fq(a: int) -> int | None:
    """Square root in Fq377 (Tonelli-Shanks via primemath.sqrt_mod)."""
    return sqrt_mod(a, Q377)


@functools.cache
def g1_generator_377() -> tuple[int, int]:
    """Deterministic G1 generator: smallest x with x^3 + 1 square, smaller
    root, cofactor-cleared into the r-torsion."""
    gx = 0
    while True:
        rhs = (gx * gx * gx + G1_B377) % Q377
        y = _sqrt_fq(rhs)
        if y is not None:
            pt = G1_HOST.scalar_mul((gx, min(y, Q377 - y)), G1_COFACTOR)
            if pt is not None:
                assert G1_HOST.is_on_curve(pt)
                assert G1_HOST.scalar_mul(pt, R377) is None, "not r-torsion"
                return pt
        gx += 1


# --------------------------------------------------------------------------
# device instances
# --------------------------------------------------------------------------


@functools.cache
def fq377():
    from .field import PrimeField

    return PrimeField(Q377)  # 24 limbs, Montgomery radix 2^384


@functools.cache
def fr377():
    from .field import PrimeField

    return PrimeField(R377)  # 16 limbs, same scalar layout as BN254


@functools.cache
def g1_377():
    """BLS12-377 G1 CurvePoints — plugs into ops/msm.py and the generic
    curve machinery (fixed-scalar ladders reduce mod this curve's own r).
    The PSS/pointNTT layers still assume BN254 Fr domains (their NTT
    tables are built over ops/constants.R) — curve-generic packed sharing
    is tracked as follow-up work, matching the reference's BLS usage
    (plain d_msm benches, dmsm_bench.rs:42-50)."""
    from .curve import CurvePoints

    nl = fq377().nl
    return CurvePoints(fq377(), G1_B377, (nl,), scalar_order=R377)


def encode_scalars_377(values):
    """Python ints -> (n, 16) standard-form u32 limbs mod r377."""
    from .scalar_pack import encode_scalars

    return encode_scalars(values, R377)


# --------------------------------------------------------------------------
# Packed secret sharing over Fr377 — the reference's BLS12-377 d_msm
# configuration (dmsm_bench.rs:42-50 packs over BLS12-377 Fr)
# --------------------------------------------------------------------------


@functools.cache
def pss377(l: int):
    """PackedSharingParams over the BLS12-377 scalar field.

    The host domains (share/secret/secret2 and the pack/unpack matrices
    derived from them) are built over r377; the in-the-exponent
    dense-ladder maps are curve-generic and ride them unchanged. Device
    FIELD-share transforms raise NotImplementedError (BN254-NTT backed) —
    scalar-share packing for this curve goes through pack_scalars_377
    (device mul-adds off the pack matrix)."""
    from ..parallel.pss import PackedSharingParams

    return PackedSharingParams(l, modulus=R377, generator=_fr_generator())


def pack_scalars_377(pp, values):
    """Pack Fr377 secrets into n Montgomery shares (scalar_pack.pack_scalars
    over PrimeField(R377); CONSECUTIVE chunking)."""
    from .scalar_pack import pack_scalars

    return pack_scalars(pp, values, fr377(), R377)
