"""BLS12-377 G1 — the second curve configuration.

The reference benchmarks its distributed kernels over ark-bls12-377
(dist-primitives/examples/dmsm_bench.rs:1,48; groth16/examples/
local_groth_bench.rs), relying on arkworks for the parameters. Here every
constant is DERIVED from the BLS12-377 seed at import and self-checked
(primality, curve membership, subgroup order), so nothing is copied on
trust:

    x  = 0x8508C00000000001                      (the BLS parameter)
    r  = x^4 - x^2 + 1                           (scalar field, 253 bits)
    q  = ((x - 1)^2 * r) / 3 + x                 (base field, 377 bits)
    G1 : y^2 = x^3 + 1 over Fq, cofactor (x-1)^2 / 3

Field elements use 24x16-bit limbs (Montgomery radix 2^384 — the generic
PrimeField of ops/field.py parameterized by limb count); scalars are Fr
elements in the standard 16-limb layout, so the MSM/ladder machinery of
ops/msm.py and ops/curve.py works unchanged. The G1 generator is chosen
deterministically (smallest-x curve point, cofactor-cleared) — generator
choice is a convention, not part of the group.

No pairing/G2 here: kernel-level curve parity (MSM, FFT-in-the-exponent,
PSS) mirrors exactly what the reference exercises over BLS12-377.
"""

from __future__ import annotations

import functools

from . import refmath as rm

# --------------------------------------------------------------------------
# parameter derivation from the seed
# --------------------------------------------------------------------------

X = 0x8508C00000000001
R377 = X**4 - X**2 + 1
Q377 = ((X - 1) ** 2 * R377) // 3 + X
G1_B377 = 1
G1_COFACTOR = (X - 1) ** 2 // 3

FR_TWO_ADICITY_377 = ((R377 - 1) & -(R377 - 1)).bit_length() - 1  # = 47


def _is_probable_prime(n: int, rounds: int = 40) -> bool:
    """Deterministic-enough Miller-Rabin (fixed small bases + pseudorandom)."""
    if n < 2:
        return False
    for sp in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % sp == 0:
            return n == sp
    d, s = n - 1, 0
    while d % 2 == 0:
        d //= 2
        s += 1
    import random

    rng = random.Random(0xB15B377)
    for i in range(rounds):
        a = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37)[i] if i < 12 else (
            rng.randrange(2, n - 1)
        )
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _pollard_rho(n: int) -> int:
    """One nontrivial factor of composite n (Brent's variant)."""
    import math
    import random

    if n % 2 == 0:
        return 2
    rng = random.Random(n)
    while True:
        y, c, m = rng.randrange(1, n), rng.randrange(1, n), 128
        g, r, q = 1, 1, 1
        while g == 1:
            x = y
            for _ in range(r):
                y = (y * y + c) % n
            k = 0
            while k < r and g == 1:
                ys = y
                for _ in range(min(m, r - k)):
                    y = (y * y + c) % n
                    q = q * abs(x - y) % n
                g = math.gcd(q, n)
                k += m
            r <<= 1
        if g == n:
            g = 1
            while g == 1:
                ys = (ys * ys + c) % n
                g = math.gcd(abs(x - ys), n)
        if g != n:
            return g


def _factor(n: int) -> set[int]:
    """Prime factors of n (recursive rho; n here has <= 64-bit parts)."""
    if n == 1:
        return set()
    if _is_probable_prime(n):
        return {n}
    d = _pollard_rho(n)
    return _factor(d) | _factor(n // d)


@functools.cache
def _fr_generator() -> int:
    """Smallest multiplicative generator of Fr377 (arkworks convention:
    smallest g whose order is r-1). r-1 = x^2 (x-1)(x+1) factors through
    64-bit integers."""
    primes = _factor(X) | _factor(X - 1) | _factor(X + 1)
    phi = R377 - 1
    g = 2
    while True:
        if all(pow(g, phi // p, R377) != 1 for p in primes):
            return g
        g += 1


# --------------------------------------------------------------------------
# self-checks (import-time; cheap)
# --------------------------------------------------------------------------

assert R377.bit_length() == 253 and Q377.bit_length() == 377
assert ((X - 1) ** 2 * R377) % 3 == 0, "q derivation divisibility"
assert _is_probable_prime(R377), "r not prime"
assert _is_probable_prime(Q377), "q not prime"
# curve/group consistency: #E(Fq) = h * r = q + 1 - t with t = x + 1
assert G1_COFACTOR * R377 == Q377 + 1 - (X + 1), "Hasse/trace identity"
assert (R377 - 1) % (1 << FR_TWO_ADICITY_377) == 0


# --------------------------------------------------------------------------
# host ground truth
# --------------------------------------------------------------------------

G1_HOST = rm._CurveOps(
    add=lambda a, b: (a + b) % Q377,
    sub=lambda a, b: (a - b) % Q377,
    mul=lambda a, b: a * b % Q377,
    sq=lambda a: a * a % Q377,
    neg=lambda a: (-a) % Q377,
    inv=lambda a: rm.finv(a, Q377),
    scalar=lambda a, k: a * k % Q377,
    zero=0,
    one=1,
    b=G1_B377,
    order=R377,
)


def _sqrt_fq(a: int) -> int | None:
    """Square root in Fq377 (q ≡ 1 mod 4 — Tonelli-Shanks, two-adicity 46)."""
    if a == 0:
        return 0
    if pow(a, (Q377 - 1) // 2, Q377) == Q377 - 1:
        return None  # non-residue
    # Tonelli-Shanks
    s = ((Q377 - 1) & -(Q377 - 1)).bit_length() - 1
    qodd = (Q377 - 1) >> s
    # any quadratic non-residue works as the generator
    z = 2
    while pow(z, (Q377 - 1) // 2, Q377) != Q377 - 1:
        z += 1
    m, c = s, pow(z, qodd, Q377)
    t, r = pow(a, qodd, Q377), pow(a, (qodd + 1) // 2, Q377)
    while t != 1:
        t2, i = t, 0
        while t2 != 1:
            t2 = t2 * t2 % Q377
            i += 1
        b = pow(c, 1 << (m - i - 1), Q377)
        m, c = i, b * b % Q377
        t, r = t * c % Q377, r * b % Q377
    return r


@functools.cache
def g1_generator_377() -> tuple[int, int]:
    """Deterministic G1 generator: smallest x with x^3 + 1 square, smaller
    root, cofactor-cleared into the r-torsion."""
    gx = 0
    while True:
        rhs = (gx * gx * gx + G1_B377) % Q377
        y = _sqrt_fq(rhs)
        if y is not None:
            pt = G1_HOST.scalar_mul((gx, min(y, Q377 - y)), G1_COFACTOR)
            if pt is not None:
                assert G1_HOST.is_on_curve(pt)
                assert G1_HOST.scalar_mul(pt, R377) is None, "not r-torsion"
                return pt
        gx += 1


# --------------------------------------------------------------------------
# device instances
# --------------------------------------------------------------------------


@functools.cache
def fq377():
    from .field import PrimeField

    return PrimeField(Q377)  # 24 limbs, Montgomery radix 2^384


@functools.cache
def fr377():
    from .field import PrimeField

    return PrimeField(R377)  # 16 limbs, same scalar layout as BN254


@functools.cache
def g1_377():
    """BLS12-377 G1 CurvePoints — plugs into ops/msm.py and the generic
    curve machinery (fixed-scalar ladders reduce mod this curve's own r).
    The PSS/pointNTT layers still assume BN254 Fr domains (their NTT
    tables are built over ops/constants.R) — curve-generic packed sharing
    is tracked as follow-up work, matching the reference's BLS usage
    (plain d_msm benches, dmsm_bench.rs:42-50)."""
    from .curve import CurvePoints

    nl = fq377().nl
    return CurvePoints(fq377(), G1_B377, (nl,), scalar_order=R377)


def encode_scalars_377(values):
    """Python ints -> (n, 16) standard-form u32 limbs mod r377."""
    import numpy as np

    import jax.numpy as jnp

    from .constants import to_limbs

    out = np.array(
        [to_limbs(int(v) % R377) for v in values], dtype=np.uint32
    )
    return jnp.asarray(out)


# --------------------------------------------------------------------------
# Packed secret sharing over Fr377 — the reference's BLS12-377 d_msm
# configuration (dmsm_bench.rs:42-50 packs over BLS12-377 Fr)
# --------------------------------------------------------------------------


@functools.cache
def pss377(l: int):
    """PackedSharingParams over the BLS12-377 scalar field.

    The host domains (share/secret/secret2 and the pack/unpack matrices
    derived from them) are built over r377; the in-the-exponent
    dense-ladder maps are curve-generic and ride them unchanged. Device
    FIELD-share transforms raise NotImplementedError (BN254-NTT backed) —
    scalar-share packing for this curve goes through pack_scalars_377
    (device mul-adds off the pack matrix)."""
    from ..parallel.pss import PackedSharingParams

    return PackedSharingParams(l, modulus=R377, generator=_fr_generator())


def pack_scalars_377(pp, values):
    """Pack Fr377 secrets l-at-a-time into n shares, device-side: one
    (n, l) matrix mul-add over PrimeField(R377) Montgomery tensors.

    values: flat list of ints (length a multiple of l, zero-padded
    otherwise). Returns (n, c, 16) Montgomery share tensors, c = len/l,
    CONSECUTIVE chunking: chunk j packs values[j*l : (j+1)*l] (the
    pack_consecutive convention — pair with identically-chunked
    packexp_from_public base shares)."""
    import jax.numpy as jnp

    F = fr377()
    vals = [int(v) % R377 for v in values]
    rem = (-len(vals)) % pp.l
    vals += [0] * rem
    c = len(vals) // pp.l
    # chunk j = (vals[j*l], ..., vals[j*l + l-1]) -> secrets of share row
    chunks = F.encode(vals)  # (c*l, 16)
    chunks = chunks.reshape(c, pp.l, 16)
    mat = F.encode([pp.pack_matrix[p][i] for p in range(pp.n)
                    for i in range(pp.l)]).reshape(pp.n, pp.l, 16)
    # out[p, j] = sum_i mat[p, i] * chunks[j, i]
    out = []
    for p in range(pp.n):
        acc = F.mul(chunks[:, 0, :], mat[p, 0][None, :])
        for i in range(1, pp.l):
            acc = F.add(acc, F.mul(chunks[:, i, :], mat[p, i][None, :]))
        out.append(acc)
    return jnp.stack(out, axis=0)  # (n, c, 16)
