"""Host number-theory helpers shared by the derived curve configurations
(ops/bls12_377.py, ops/bls12_381.py): primality, factoring, square roots.
Pure-bigint, import-time cheap."""

from __future__ import annotations

import math
import random


def is_probable_prime(n: int, rounds: int = 40) -> bool:
    """Deterministic-enough Miller-Rabin (fixed small bases + pseudorandom)."""
    if n < 2:
        return False
    for sp in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % sp == 0:
            return n == sp
    d, s = n - 1, 0
    while d % 2 == 0:
        d //= 2
        s += 1
    rng = random.Random(0xB15B377)
    for i in range(rounds):
        a = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37)[i] if i < 12 else (
            rng.randrange(2, n - 1)
        )
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def pollard_rho(n: int) -> int:
    """One nontrivial factor of composite n (Brent's variant)."""
    if n % 2 == 0:
        return 2
    rng = random.Random(n)
    while True:
        y, c, m = rng.randrange(1, n), rng.randrange(1, n), 128
        g, r, q = 1, 1, 1
        while g == 1:
            x = y
            for _ in range(r):
                y = (y * y + c) % n
            k = 0
            while k < r and g == 1:
                ys = y
                for _ in range(min(m, r - k)):
                    y = (y * y + c) % n
                    q = q * abs(x - y) % n
                g = math.gcd(q, n)
                k += m
            r <<= 1
        if g == n:
            g = 1
            while g == 1:
                ys = (ys * ys + c) % n
                g = math.gcd(abs(x - ys), n)
        if g != n:
            return g


def factor(n: int) -> set[int]:
    """Prime factors of n (recursive rho; intended for <= ~128-bit n)."""
    if n == 1:
        return set()
    if is_probable_prime(n):
        return {n}
    d = pollard_rho(n)
    return factor(d) | factor(n // d)


def smallest_generator(r: int, phi_primes: set[int]) -> int:
    """Smallest multiplicative generator of F_r given the prime factors of
    r - 1 (arkworks' GENERATOR convention)."""
    phi = r - 1
    g = 2
    while True:
        if all(pow(g, phi // p, r) != 1 for p in phi_primes):
            return g
        g += 1


def sqrt_mod(a: int, q: int) -> int | None:
    """Square root mod prime q (Tonelli-Shanks; None for non-residues)."""
    a %= q
    if a == 0:
        return 0
    if pow(a, (q - 1) // 2, q) == q - 1:
        return None
    if q % 4 == 3:
        return pow(a, (q + 1) // 4, q)
    s = ((q - 1) & -(q - 1)).bit_length() - 1
    qodd = (q - 1) >> s
    z = 2
    while pow(z, (q - 1) // 2, q) != q - 1:
        z += 1
    m, c = s, pow(z, qodd, q)
    t, r = pow(a, qodd, q), pow(a, (qodd + 1) // 2, q)
    while t != 1:
        t2, i = t, 0
        while t2 != 1:
            t2 = t2 * t2 % q
            i += 1
        b = pow(c, 1 << (m - i - 1), q)
        m, c = i, b * b % q
        t, r = t * c % q, r * b % q
    return r


def fq2_mul(a, b, q: int):
    """(a0 + a1 u)(b0 + b1 u) in Fq[u]/(u^2+1), any prime q — the shared
    tower multiply (refmath's fq2_* are BN254-bound)."""
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % q, (a0 * b1 + a1 * b0) % q)


def fq2_inv(a, q: int):
    """1/(a0 + a1 u) via the conjugate/norm map, any prime q."""
    a0, a1 = a
    n = pow((a0 * a0 + a1 * a1) % q, q - 2, q)
    return (a0 * n % q, (-a1) * n % q)
