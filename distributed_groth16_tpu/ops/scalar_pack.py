"""Curve-generic scalar encoding + packed-share construction, shared by
the BLS curve configurations (ops/bls12_377.py, ops/bls12_381.py).

The BN254 path has its own device-NTT packing (parallel/pss.py); for
other scalar fields the pack map is applied as an explicit (n, l) matrix
mul-add over the field's PrimeField tensors."""

from __future__ import annotations


def encode_scalars(values, r: int):
    """Python ints -> (n, 16) standard-form u32 limbs mod r (r < 2^256)."""
    import jax.numpy as jnp
    import numpy as np

    from .constants import to_limbs

    out = np.array([to_limbs(int(v) % r) for v in values], dtype=np.uint32)
    return jnp.asarray(out)


def pack_scalars(pp, values, F, r: int):
    """Pack secrets l-at-a-time into n Montgomery share tensors,
    device-side: out[p, j] = sum_i M[p][i] * chunk_j[i] over PrimeField F
    (F.nl carries the limb count — 16 for r377, 17 for r381).

    CONSECUTIVE chunking: chunk j packs values[j*l : (j+1)*l] (the
    pack_consecutive convention — pair with identically-chunked
    packexp_from_public base shares). Returns (n, c, F.nl)."""
    import jax.numpy as jnp

    nl = F.nl
    vals = [int(v) % r for v in values]
    vals += [0] * ((-len(vals)) % pp.l)
    c = len(vals) // pp.l
    chunks = F.encode(vals).reshape(c, pp.l, nl)
    mat = F.encode(
        [pp.pack_matrix[p][i] for p in range(pp.n) for i in range(pp.l)]
    ).reshape(pp.n, pp.l, nl)
    out = []
    for p in range(pp.n):
        acc = F.mul(chunks[:, 0, :], mat[p, 0][None, :])
        for i in range(1, pp.l):
            acc = F.add(acc, F.mul(chunks[:, i, :], mat[p, i][None, :]))
        out.append(acc)
    return jnp.stack(out, axis=0)
