"""Windowed fixed-base scalar multiplication — the setup/CRS workhorse.

Every scalar multiplication in Groth16 setup shares ONE base (the G1/G2
generator), so the 256-step double-and-add ladder is wasteful: precompute
T[w][d] = d * 2^(c*w) * G once (host affine arithmetic, ops/refmath.py),
then each scalar costs W-1 = 31 batched complete additions of table
gathers — 16x fewer curve ops than the ladder, and a single add
instantiation (compile-light, see VERDICT r2 weak #3/#5).

Replaces the per-element generator ladders of the reference's
circuit_specific_setup (the reference leans on arkworks
`fixed_base::FixedBase::msm` which uses the same windowed-table idea —
role parity, independent implementation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import refmath as rm
from .constants import G1_GENERATOR, G2_GENERATOR, LIMB_BITS
from .curve import CurvePoints, g1, g2

WINDOW_C = 8  # digits per window; divides the 16-bit limb
N_WINDOWS = 256 // WINDOW_C


def _host_table(host_ops, base_affine):
    """(W, 2^c) affine host points: row w holds d * 2^(c*w) * B."""
    rows = []
    bw = base_affine
    for _ in range(N_WINDOWS):
        row = [None, bw]
        for _ in range(2, 1 << WINDOW_C):
            row.append(host_ops.add(row[-1], bw))
        rows.append(row)
        for _ in range(WINDOW_C):
            bw = host_ops.double(bw)
    return rows


@functools.cache
def generator_table(which: str) -> jnp.ndarray:
    """Device table (W, 2^c, 3) + elem for the G1/G2 generator."""
    if which == "g1":
        rows = _host_table(rm.G1, G1_GENERATOR)
        curve = g1()
    else:
        rows = _host_table(rm.G2, G2_GENERATOR)
        curve = g2()
    flat = [p for row in rows for p in row]
    enc = curve.encode(flat)
    return enc.reshape((N_WINDOWS, 1 << WINDOW_C) + enc.shape[1:])


def _digits(scalars_std: jnp.ndarray) -> jnp.ndarray:
    """(n, 16) standard-form u32 limbs -> (n, W) int32 c-bit digits."""
    w = np.arange(N_WINDOWS)
    limb_idx = (w * WINDOW_C) // LIMB_BITS
    shift = jnp.asarray((w * WINDOW_C) % LIMB_BITS, jnp.uint32)
    limbs = scalars_std[:, limb_idx]
    return ((limbs >> shift) & ((1 << WINDOW_C) - 1)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0,))
def _fixed_base_jit(curve: CurvePoints, table, scalars_std):
    digits = _digits(scalars_std)  # (n, W)
    n = scalars_std.shape[0]
    acc0 = jnp.broadcast_to(curve.infinity(), (n, 3) + curve.elem_shape)

    def body(w, acc):
        pts = table[w][digits[:, w]]  # (n, 3)+elem gather
        return curve.add(acc, pts)

    return jax.lax.fori_loop(0, N_WINDOWS, body, acc0)


# -- host-side windowed mul for arbitrary fixed bases ------------------------
# The verifier's prepare_inputs fallback (models/groth16/verify.py): each
# gamma_abc base is fixed per circuit and re-multiplied on every
# verification, so the same table idea pays on pure host bigint math. A
# narrower window keeps the one-time table build cheap: c=4 costs
# 64 x 14 = 896 adds to build and <= 63 adds + 63 doublings-equivalent
# gathers per mul, vs ~384 adds/doubles for one 256-bit ladder — the
# table wins from the third multiplication on a base onward.

_HOST_WINDOW_C = 4
_HOST_N_WINDOWS = 256 // _HOST_WINDOW_C


@functools.lru_cache(maxsize=256)
def _host_mul_table(which: str, base_affine):
    """(W, 2^c) affine host rows for ANY base: row w holds
    d * 2^(c*w) * B. Cached per (group, base) — affine points are nested
    int tuples, hence hashable."""
    host_ops = rm.G1 if which == "g1" else rm.G2
    rows = []
    bw = base_affine
    for _ in range(_HOST_N_WINDOWS):
        row = [None, bw]
        for _ in range(2, 1 << _HOST_WINDOW_C):
            row.append(host_ops.add(row[-1], bw))
        rows.append(row)
        for _ in range(_HOST_WINDOW_C):
            bw = host_ops.double(bw)
    return rows


def host_windowed_mul(which: str, base_affine, k: int):
    """k * base on host ("g1" | "g2") through the cached windowed table.
    None base (infinity) and k == 0 mod order return None, matching the
    refmath ladder."""
    host_ops = rm.G1 if which == "g1" else rm.G2
    k %= host_ops.order
    if base_affine is None or k == 0:
        return None
    rows = _host_mul_table(which, base_affine)
    mask = (1 << _HOST_WINDOW_C) - 1
    acc = None
    for w in range(_HOST_N_WINDOWS):
        d = (k >> (w * _HOST_WINDOW_C)) & mask
        if d:
            acc = host_ops.add(acc, rows[w][d])
    return acc


def fixed_base_mul(which: str, scalars_std, chunk: int = 1 << 19):
    """scalars (n, 16) standard-form u32 -> (n, 3)+elem projective points
    scalar * G on the named generator ("g1" | "g2"). Chunked to bound peak
    memory at million scale."""
    curve = g1() if which == "g1" else g2()
    table = generator_table(which)
    n = scalars_std.shape[0]
    if n <= chunk:
        return _fixed_base_jit(curve, table, scalars_std)
    parts = [
        _fixed_base_jit(curve, table, scalars_std[s : s + chunk])
        for s in range(0, n, chunk)
    ]
    return jnp.concatenate(parts, axis=0)
