"""Multi-scalar multiplication (Pippenger) for BN254 G1/G2 on JAX/TPU.

Computes sum_i s_i * P_i — the dominant kernel of the Groth16 prover (the
reference's per-party hot loop is arkworks `G::msm` at
dist-primitives/src/dmsm/mod.rs:82, called five times per proof:
S*a, V*a, W*ax, U*h, H*a — groth16/src/prove.rs).

TPU-first design — no scatter, no data-dependent control flow:

  * windowed digits: each 254-bit scalar is split into W = 256/c digits of
    c bits (c | 16 so digits never straddle the uint16 limbs of ops/field.py).
  * bucket accumulation WITHOUT scatter: per window, points are sorted by
    digit (one argsort of int32 keys) and an inclusive prefix sum of the
    sorted points is taken under the branchless group law
    (`lax.associative_scan` — log-depth, fully batched adds). The sum of
    bucket b is then prefix[end_b] - prefix[end_{b-1}], and the classic
    weighted-bucket identity
        sum_b b * S_b = sum_{k=1..B-1} (T - C_{k-1})
    (T = sum of all points, C_j = prefix sum through bucket j) turns the
    whole window reduction into B batched complete-adds + one tree sum.
  * window combine is Horner: c doublings + 1 add per window.

Complete RCB16 formulas (ops/curve.py) make every add branchless, so the
entire MSM is one `jit`-compiled program of static shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..telemetry import metrics as _tm
from .constants import LIMB_BITS, N_LIMBS
from .curve import CurvePoints, g1, g2

# total scalar bits covered (BN254 Fr fits in 254 < 256)
_SCALAR_BITS = 256

# which implementation actually ran (docs/OBSERVABILITY.md): production
# dashboards catch a TPU mesh silently falling back to the generic path.
# Counted at dispatch time — under an enclosing jit that is once per
# traced signature, not per execution.
_ROUTE = _tm.registry().counter(
    "kernel_route_total",
    "Kernel-path routing decisions at dispatch/trace time, per kernel "
    "and chosen implementation path",
    ("kernel", "path"),
)
# pre-bound children (the metrics.py hot-path contract: one dict lookup
# + add per record, no per-call label-tuple allocation)
_R_TREE = _ROUTE.labels(kernel="msm", path="tree")
_R_LADDER = _ROUTE.labels(kernel="msm", path="ladder")
_R_PIPPENGER = _ROUTE.labels(kernel="msm", path="pippenger")
_R_CHUNKED = _ROUTE.labels(kernel="msm", path="pippenger_chunked")
_RB_TREE = _ROUTE.labels(kernel="msm_batched", path="tree")
_RB_LADDER = _ROUTE.labels(kernel="msm_batched", path="ladder")
_RB_VMAP = _ROUTE.labels(kernel="msm_batched", path="pippenger_vmap")


def _digits_for_window(scalars, w, c: int):
    """Extract the w-th c-bit digit of each scalar. scalars: (n, 16) standard
    form; w may be traced. Returns (n,) int32 in [0, 2^c)."""
    per_limb = LIMB_BITS // c
    limb_idx = w // per_limb
    shift = (w % per_limb) * c
    limb = jax.lax.dynamic_index_in_dim(scalars, limb_idx, axis=-1, keepdims=False)
    return ((limb >> shift) & ((1 << c) - 1)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 3))
def _msm_jit(curve: CurvePoints, points, scalars, c: int):
    n = points.shape[0]
    B = 1 << c
    W = _SCALAR_BITS // c
    inf = curve.infinity()

    def window_sum(w):
        digits = _digits_for_window(scalars, w, c)
        order = jnp.argsort(digits)
        d_sorted = jnp.take(digits, order, axis=0)
        p_sorted = jnp.take(points, order, axis=0)
        prefix = jax.lax.associative_scan(curve.add, p_sorted, axis=0)
        total = prefix[n - 1]
        # C_j = sum of points with digit <= j, for j = 0..B-2
        ends = jnp.searchsorted(d_sorted, jnp.arange(B - 1), side="right")
        cum = curve.select(
            ends > 0,
            jnp.take(prefix, jnp.maximum(ends - 1, 0), axis=0),
            jnp.broadcast_to(inf, (B - 1,) + inf.shape),
        )
        # sum_b b*S_b = sum_{j=0..B-2} (total - C_j)
        terms = curve.add(jnp.broadcast_to(total, cum.shape), curve.neg(cum))
        return curve.sum(terms, axis=0)

    def body(i, acc):
        w = W - 1 - i

        def dbl(_, a):
            return curve.double(a)

        acc = jax.lax.fori_loop(0, c, dbl, acc)
        return curve.add(acc, window_sum(w))

    return jax.lax.fori_loop(0, W, body, inf)


# below this point count the one-ladder MSM wins on compile time (2 curve-op
# instantiations vs ~10 for a Pippenger window body — each instance costs
# seconds of XLA:CPU compile) and its 256·n runtime is negligible anyway
_LADDER_MSM_MAX_N = 128


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_ladder_jit(curve: CurvePoints, points, scalars):
    """Small-n MSM as one batched double-and-add ladder + a sequential
    accumulation: the compile-light path (1 add + 1 double + 1 acc-add
    instantiation). Same results as _msm_jit."""
    from .curve import scalar_bits

    acc = curve.scalar_mul_bits(points, scalar_bits(scalars))
    return curve.sum_sequential(acc, axis=0)


def _limb_group_for(curve: CurvePoints):
    """The LimbGroup factory matching this curve's base field + extension
    degree, or None for unsupported configurations. BN254 and
    BLS12-377/381 all ride the same limb machinery (LimbField is
    limb-count-generic as of r5)."""
    from . import limb_kernels as lk
    from .constants import Q as _BN254_Q

    base_p = curve.F.p if hasattr(curve.F, "p") else curve.F.fq.p
    ext2 = len(curve.elem_shape) == 2
    if base_p == _BN254_Q:
        return lk.lg2 if ext2 else lk.lg1
    from .bls12_377 import Q377
    from .bls12_381 import Q381

    if base_p == Q377 and not ext2:
        return lk.lg1_377
    if base_p == Q381:
        return lk.lg2_381 if ext2 else lk.lg1_381
    return None


def _tree_group(curve: CurvePoints, n: int):
    """The LimbGroup to run this MSM's limb-major tree path on, or None
    for the generic row-major path. TPU backends route every supported
    curve here — the Pallas fast path; DG16_FORCE_TREE_MSM=1 forces it
    anywhere (tests exercise the identical XLA bodies on CPU)."""
    from ..utils import config as _config

    factory = _limb_group_for(curve)
    if factory is None:
        return None
    if _config.env_flag("DG16_FORCE_TREE_MSM"):
        return factory()
    from .limb_kernels import use_pallas

    return factory() if (use_pallas() and n >= 1024) else None



def msm(curve: CurvePoints, points, scalars, window_bits: int | None = None,
        chunk: int | None = None):
    """sum_i scalars[i] * points[i].

    points:  (n, 3) + elem_shape projective device points.
    scalars: (n, 16) uint32 limbs in STANDARD (non-Montgomery) form.
    window_bits: Pippenger window c (must divide 16); default auto.
    chunk: process points in chunks of this size (bounds peak memory; MSM is
           linear so chunk results just add).

    Returns a single projective point (3,) + elem_shape.
    """
    n = points.shape[0]
    # scalar layouts wider than 16 limbs (r381's 17-limb standard form)
    # are accepted: every supported scalar order is < 2^256, so the extra
    # limbs are zero; the tree path's digit decomposition is width-aware
    # and the Pippenger/ladder paths read 256 bits
    assert scalars.shape[-1] >= N_LIMBS and scalars.shape[0] == n
    # explicit window_bits/chunk pin the generic path (chunk in particular
    # is a memory bound the tree path would silently drop)
    tree_g = (
        _tree_group(curve, n) if window_bits is None and chunk is None
        else None
    )
    if tree_g is not None:
        from .limb_kernels import msm_tree

        _R_TREE.inc()
        return msm_tree(points, scalars, group=tree_g)
    if window_bits is None and chunk is None and n <= _LADDER_MSM_MAX_N:
        _R_LADDER.inc()
        return _msm_ladder_jit(curve, points, scalars)
    if window_bits is None:
        # the sort+scan bucketing costs ~n log n adds per window, so fewer,
        # wider windows win once n dwarfs the 2^c bucket-combine cost
        window_bits = 16 if n >= (1 << 14) else 8 if n >= 64 else 4
    assert LIMB_BITS % window_bits == 0, "window must divide the 16-bit limb"
    if chunk is None or chunk >= n:
        _R_PIPPENGER.inc()
        return _msm_jit(curve, points, scalars, window_bits)
    _R_CHUNKED.inc()
    acc = curve.infinity()
    for s in range(0, n, chunk):
        part = _msm_jit(curve, points[s : s + chunk], scalars[s : s + chunk],
                        window_bits)
        acc = curve.add(acc, part)
    return acc


def msm_batched(curve: CurvePoints, bases, scalars_std):
    """B same-length MSMs: (B, n, 3)+elem x (B, n, 16) std-form scalars ->
    (B, 3)+elem. Single routing point shared with msm() (incl. the
    DG16_FORCE_TREE_MSM override): Pallas tree kernels per MSM on TPU G1,
    one batched ladder at small n, ONE vmapped Pippenger otherwise (a
    Python loop of Pippengers put B bodies in the traced graph and the
    m=4096 mesh-prover compile took 13+ minutes)."""
    B, n = scalars_std.shape[0], scalars_std.shape[1]
    tree_g = _tree_group(curve, n)
    if tree_g is not None:
        from .limb_kernels import msm_tree

        _RB_TREE.inc()
        return jnp.stack(
            [
                msm_tree(bases[b], scalars_std[b], group=tree_g)
                for b in range(B)
            ]
        )
    if n <= _LADDER_MSM_MAX_N:
        from .curve import scalar_bits

        _RB_LADDER.inc()
        acc = curve.scalar_mul_bits(bases, scalar_bits(scalars_std))
        return curve.sum_sequential(acc, axis=1)
    _RB_VMAP.inc()
    wbits = 16 if n >= (1 << 14) else 8 if n >= 64 else 4
    return jax.vmap(lambda bs, sc: _msm_jit(curve, bs, sc, wbits))(
        bases, scalars_std
    )


def msm_g1(points, scalars, **kw):
    return msm(g1(), points, scalars, **kw)


def msm_g2(points, scalars, **kw):
    return msm(g2(), points, scalars, **kw)


def encode_scalars_std(values) -> jnp.ndarray:
    """Python ints -> (n, 16) standard-form uint32 limb array (host-side)."""
    import numpy as np

    from .constants import R, to_limbs

    vals = [int(v) % R for v in values]
    out = np.array([to_limbs(v) for v in vals], dtype=np.uint32)
    return jnp.asarray(out)
