"""Vectorized prime-field arithmetic for JAX/TPU.

Field elements live on device as uint32 tensors of shape (..., 16): sixteen
16-bit little-endian limbs, in Montgomery form (R = 2^256). All arithmetic is
expressed in pure uint32 vector ops, which map onto the TPU VPU; products of
16-bit limbs fit exactly in uint32, and the Montgomery CIOS inner loop is
implemented with *lazy carries* — limb accumulators only approach ~2^22 before
a single final carry propagation — so each full 254-bit multiply is ~16 fused
vector steps over the batch.

This layer has no counterpart file in the reference (arkworks provides native
field arithmetic); it is the TPU-native replacement for ark-ff as used
throughout dist-primitives and groth16.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .constants import LIMB_BITS, LIMB_MASK, N_LIMBS, Q, R, to_limbs

_MASK = np.uint32(LIMB_MASK)


def _limbs_np(x: int, n_limbs: int = N_LIMBS) -> np.ndarray:
    return np.array(to_limbs(x, n_limbs), dtype=np.uint32)


class PrimeField:
    """Montgomery arithmetic over a fixed prime, vectorized over leading axes.

    All public methods take/return uint32 arrays of shape (..., 16) holding
    canonical (< p) Montgomery-form values, unless noted otherwise.
    """

    def __init__(self, modulus: int, n_limbs: int | None = None):
        # limb count: 16 for <=256-bit moduli (BN254), 24 for 377/381-bit
        # curves (BLS12-377/381). Montgomery radix follows: 2^(16 * nl).
        # Redundancy invariant 4p < 2^(16*nl) must hold for lazy-carry CIOS.
        self.nl = n_limbs or max(
            N_LIMBS, -(-(modulus.bit_length() + 2) // LIMB_BITS)
        )
        assert 4 * modulus < 1 << (LIMB_BITS * self.nl)
        self.p = modulus
        self.mont_bits = LIMB_BITS * self.nl
        self.mont_r = (1 << self.mont_bits) % modulus
        self.mont_r2 = self.mont_r * self.mont_r % modulus
        self.mont_rinv = pow(self.mont_r, modulus - 2, modulus)
        # -p^{-1} mod 2^16 for the CIOS reduction step
        self.n0 = np.uint32((-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        self.p_limbs = _limbs_np(modulus, self.nl)
        self.one = _limbs_np(self.mont_r, self.nl)  # 1 in Montgomery form
        self.zero = np.zeros(self.nl, dtype=np.uint32)
        self.r2 = _limbs_np(self.mont_r2, self.nl)
        # exponent bits for Fermat inversion, LSB first
        e = modulus - 2
        self._inv_bits = np.array(
            [(e >> i) & 1 for i in range(e.bit_length())], dtype=np.uint32
        )
        # jit-wrap the public ring ops so eager call sites (tests, host glue)
        # hit the compiled-executable cache instead of per-primitive dispatch.
        for name in ("add", "sub", "neg", "mul", "sqr", "inv", "batch_inv",
                     "to_mont", "from_mont"):
            setattr(self, name, jax.jit(getattr(self, name)))

    # -- host <-> device conversion -------------------------------------------

    def encode_np(self, values) -> np.ndarray:
        """Python ints / nested lists -> Montgomery limb array as NUMPY.
        Safe to build and cache from inside a jit trace (a numpy array is
        a plain constant, never a tracer); use for tables stored on
        long-lived cached objects (JaxDomain) that may first be
        constructed under a trace."""
        arr = np.asarray(values, dtype=object)
        p, r = self.p, self.mont_r
        nb = 2 * self.nl
        buf = b"".join(
            ((int(v) % p) * r % p).to_bytes(nb, "little") for v in arr.reshape(-1)
        )
        out = np.frombuffer(buf, dtype="<u2").astype(np.uint32)
        return out.reshape(arr.shape + (self.nl,))

    def encode(self, values) -> jnp.ndarray:
        """Python ints / nested lists -> Montgomery limb array (host-side)."""
        return jnp.asarray(self.encode_np(values))

    def decode(self, x) -> np.ndarray:
        """Montgomery limb array -> numpy object array of Python ints."""
        arr = np.asarray(x)
        nl, nb = self.nl, 2 * self.nl
        flat = arr.reshape(-1, nl).astype("<u2").tobytes()
        n = arr.size // nl
        rinv, p = self.mont_rinv, self.p
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = (
                int.from_bytes(flat[nb * i : nb * (i + 1)], "little") * rinv % p
            )
        return out.reshape(arr.shape[:-1])

    def consts(self, shape=()):
        """(zero, one) broadcast to the given batch shape."""
        z = jnp.broadcast_to(jnp.asarray(self.zero), shape + (self.nl,))
        o = jnp.broadcast_to(jnp.asarray(self.one), shape + (self.nl,))
        return z, o

    # -- carry machinery ------------------------------------------------------
    #
    # Carry/borrow chains are `lax.scan`s over the limb axis: the body is one
    # vector op over the whole batch, so the traced graph stays tiny (XLA
    # compile time of composite kernels was dominated by unrolled chains) and
    # the compiled loop runs limb-major with good locality.

    @staticmethod
    def _carry_propagate_limb_major(vt):
        """Carry propagation of a (k,) + batch limb-major lazy accumulator."""

        def step(c, x):
            t = x + c
            return t >> LIMB_BITS, t & _MASK

        _, out = jax.lax.scan(step, jnp.zeros(vt.shape[1:], jnp.uint32), vt)
        return out

    @classmethod
    def _carry_propagate(cls, v):
        """Full carry propagation of a (..., k)-limb lazy accumulator."""
        vt = jnp.moveaxis(v, -1, 0)
        return jnp.moveaxis(cls._carry_propagate_limb_major(vt), 0, -1)

    @staticmethod
    def _sub_limbs(a, b):
        """Limb-wise a - b with borrow chain; returns (diff, final_borrow).

        Both inputs carried (limbs <= LIMB_MASK); borrow detection relies on
        uint32 wraparound setting the top bit.
        """
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        at = jnp.moveaxis(jnp.broadcast_to(a, shape), -1, 0)
        bt = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)

        def step(borrow, xs):
            x, y = xs
            t = x - y - borrow
            return t >> 31, t & _MASK  # top bit set iff subtraction went negative

        borrow, out = jax.lax.scan(
            step, jnp.zeros(shape[:-1], jnp.uint32), (at, bt)
        )
        return jnp.moveaxis(out, 0, -1), borrow

    def _sub_p_if_geq(self, a):
        """a - p if a >= p else a (a < 2p, 16 limbs, carried)."""
        p = jnp.broadcast_to(jnp.asarray(self.p_limbs), a.shape)
        d, borrow = self._sub_limbs(a, p)
        return jnp.where((borrow == 0)[..., None], d, a)

    # -- ring ops -------------------------------------------------------------

    def add(self, a, b):
        return self._sub_p_if_geq(self._carry_propagate(a + b))

    def sub(self, a, b):
        # a + (p - b); p - b computed with borrow chain (b canonical -> no
        # underflow overall)
        p = jnp.broadcast_to(jnp.asarray(self.p_limbs), b.shape)
        pb, _ = self._sub_limbs(p, b)
        # b == 0 -> p - b == p which is non-canonical; add() reduces it.
        return self.add(a, pb)

    def neg(self, a):
        z = jnp.zeros_like(a)
        return self.sub(z, a)

    def mul(self, a, b):
        """Montgomery product abR^{-1} mod p, lazy-carry CIOS.

        The 16 CIOS iterations run under `lax.scan` with a shape-uniform
        body, so the traced graph is one butterfly-sized block regardless of
        how many muls a caller composes — this keeps XLA compile times of big
        composite kernels (curve adds, NTT stages) tractable.
        """
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        batch = shape[:-1]
        # limb-major layout inside the kernel: (limb,) + batch
        at = jnp.moveaxis(jnp.broadcast_to(a, shape), -1, 0)
        bt = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)
        qt = jnp.asarray(self.p_limbs).reshape((self.nl,) + (1,) * len(batch))
        pad_lo = [(0, 1)] + [(0, 0)] * len(batch)
        pad_hi = [(1, 0)] + [(0, 0)] * len(batch)
        zeros_head = jnp.zeros((1,) + batch, jnp.uint32)

        def step(v, ai):
            prod = ai[None] * bt
            v = v + jnp.pad(prod & _MASK, pad_lo) + jnp.pad(prod >> LIMB_BITS, pad_hi)
            m = (v[0] * self.n0) & _MASK
            qp = m[None] * qt
            v = v + jnp.pad(qp & _MASK, pad_lo) + jnp.pad(qp >> LIMB_BITS, pad_hi)
            # limb 0 is now ≡ 0 mod 2^16; shift right one limb, pushing its
            # high bits into the new limb 0.
            carry0 = v[0] >> LIMB_BITS
            return (
                jnp.concatenate([(v[1] + carry0)[None], v[2:], zeros_head], axis=0),
                None,
            )

        v, _ = jax.lax.scan(step, jnp.zeros((self.nl + 1,) + batch, jnp.uint32), at)
        v = jnp.moveaxis(self._carry_propagate_limb_major(v)[: self.nl], 0, -1)
        return self._sub_p_if_geq(v)

    def sqr(self, a):
        return self.mul(a, a)

    def to_mont(self, a_std):
        """Standard-form limbs -> Montgomery form (device-side)."""
        return self.mul(a_std, jnp.asarray(self.r2))

    def from_mont(self, a_mont):
        """Montgomery form -> standard-form limbs (device-side)."""
        one_std = jnp.zeros(self.nl, jnp.uint32).at[0].set(1)
        return self.mul(a_mont, jnp.broadcast_to(one_std, a_mont.shape))

    # -- predicates -----------------------------------------------------------

    def eq(self, a, b):
        return jnp.all(a == b, axis=-1)

    def is_zero(self, a):
        return jnp.all(a == 0, axis=-1)

    def select(self, cond, a, b):
        """where(cond, a, b) with cond of batch shape."""
        return jnp.where(cond[..., None], a, b)

    # -- exponentiation / inversion -------------------------------------------

    def pow_bits(self, x, bits: np.ndarray):
        """x^e where e is given LSB-first as a static 0/1 numpy array."""
        bits_d = jnp.asarray(bits)
        one = jnp.broadcast_to(jnp.asarray(self.one), x.shape)

        def body(i, state):
            acc, base = state
            take = bits_d[i] == 1
            acc = jnp.where(take, self.mul(acc, base), acc)
            return acc, self.mul(base, base)

        acc, _ = jax.lax.fori_loop(0, len(bits), body, (one, x))
        return acc

    def inv(self, x):
        """Elementwise Fermat inversion x^(p-2). inv(0) = 0."""
        return self.pow_bits(x, self._inv_bits)

    def batch_inv(self, x):
        """Batch inversion over the leading axis via prefix products.

        x: (n, ..., 16). Cost: 3n muls + one Fermat inversion. Zero entries
        produce zero outputs (handled by substituting 1 and masking).
        """
        one = jnp.broadcast_to(jnp.asarray(self.one), x.shape[1:])
        zmask = self.is_zero(x)
        x_safe = jnp.where(zmask[..., None], one, x)

        def fwd(carry, xi):
            nxt = self.mul(carry, xi)
            return nxt, carry  # prefix[i] = x0*...*x_{i-1}

        total, prefix = jax.lax.scan(fwd, one, x_safe)
        tinv = self.inv(total)

        def bwd(carry, inp):
            xi, pre = inp
            out = self.mul(carry, pre)
            return self.mul(carry, xi), out

        _, out = jax.lax.scan(bwd, tinv, (x_safe, prefix), reverse=True)
        return jnp.where(zmask[..., None], jnp.zeros_like(out), out)


@functools.cache
def fq() -> PrimeField:
    return PrimeField(Q)


@functools.cache
def fr() -> PrimeField:
    return PrimeField(R)


# ---------------------------------------------------------------------------
# Fq2 = Fq[u]/(u^2+1): elements are (..., 2, 16) uint32 (Montgomery limbs).
# ---------------------------------------------------------------------------


class Fq2Ops:
    def __init__(self, base: PrimeField):
        self.fq = base

    def encode(self, values):
        """List/array of (c0, c1) int pairs -> (..., 2, 16)."""
        return self.fq.encode(values)

    def decode(self, x):
        return self.fq.decode(x)

    def add(self, a, b):
        return self.fq.add(a, b)

    def sub(self, a, b):
        return self.fq.sub(a, b)

    def neg(self, a):
        return self.fq.neg(a)

    def mul(self, a, b):
        f = self.fq
        a0, a1 = a[..., 0, :], a[..., 1, :]
        b0, b1 = b[..., 0, :], b[..., 1, :]
        t0 = f.mul(a0, b0)
        t1 = f.mul(a1, b1)
        s = f.mul(f.add(a0, a1), f.add(b0, b1))
        c0 = f.sub(t0, t1)
        c1 = f.sub(s, f.add(t0, t1))
        return jnp.stack([c0, c1], axis=-2)

    def sqr(self, a):
        f = self.fq
        a0, a1 = a[..., 0, :], a[..., 1, :]
        t = f.mul(a0, a1)
        c0 = f.mul(f.add(a0, a1), f.sub(a0, a1))
        c1 = f.add(t, t)
        return jnp.stack([c0, c1], axis=-2)

    def scalar_fq(self, a, k):
        """Multiply both coefficients by an Fq element k (..., 16)."""
        return jnp.stack(
            [self.fq.mul(a[..., 0, :], k), self.fq.mul(a[..., 1, :], k)], axis=-2
        )

    def inv(self, a):
        f = self.fq
        a0, a1 = a[..., 0, :], a[..., 1, :]
        norm = f.add(f.sqr(a0), f.sqr(a1))
        ninv = f.inv(norm)
        return jnp.stack([f.mul(a0, ninv), f.neg(f.mul(a1, ninv))], axis=-2)

    def is_zero(self, a):
        return jnp.all(a == 0, axis=(-1, -2))

    def eq(self, a, b):
        return jnp.all(a == b, axis=(-1, -2))

    def consts(self, shape=()):
        nl = self.fq.nl  # limb count follows the base field (24 for BLS)
        z = jnp.broadcast_to(jnp.asarray(self.fq.zero), shape + (2, nl))
        one = np.zeros((2, nl), np.uint32)
        one[0] = self.fq.one
        o = jnp.broadcast_to(jnp.asarray(one), shape + (2, nl))
        return z, o


@functools.cache
def fq2() -> Fq2Ops:
    return Fq2Ops(fq())
