"""BN254 curve and field constants.

Reference parity: the reference framework (zkHubHQ/distributed-groth16) uses
arkworks' ark-bn254 (and ark-bls12-377 in some examples). We standardise on
BN254 (alt_bn128), the curve of the Groth16 service path and of all circom
fixtures (ark-circom/src/circom/r1cs_reader.rs:163-189 hardcodes the 32-byte
BN254 prime).

Domain/FFT conventions match ark-poly's Radix2EvaluationDomain: the size-N
root of unity is GENERATOR^((r-1)/N) with GENERATOR the smallest multiplicative
generator of Fr (5 for BN254), and cosets use offset = GENERATOR
(secret-sharing/src/pss.rs:39-47).
"""

# ---------------------------------------------------------------------------
# BN254 (alt_bn128) parameters
# ---------------------------------------------------------------------------

# Base field modulus q and scalar field modulus r.
Q = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# BN parameter x: q(x), r(x), t(x) are the standard BN polynomials.
BN_X = 4965661367192848881

# Multiplicative generators (smallest) — match arkworks' Fr::GENERATOR /
# Fq::GENERATOR used for coset offsets.
FR_GENERATOR = 5
FQ_GENERATOR = 3

# Two-adicity of r - 1 (28 for BN254 Fr).
FR_TWO_ADICITY = 28
# 2^28-th primitive root of unity in Fr, arkworks convention.
FR_TWO_ADIC_ROOT = pow(FR_GENERATOR, (R - 1) >> FR_TWO_ADICITY, R)

# G1: y^2 = x^3 + 3 over Fq
G1_B = 3
G1_GENERATOR = (1, 2)

# G2: y^2 = x^3 + b/xi over Fq2 = Fq[u]/(u^2+1), xi = 9 + u (D-type twist).
FQ2_NON_RESIDUE = (9, 1)  # xi
# b' = 3 / (9 + u)
G2_B = (
    19485874751759354771024239261021720505790618469301721065564631296452457478373,
    266929791119991161246907387137283842545076965332900288569378510910307636690,
)
G2_GENERATOR = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# ate pairing loop count: 6x + 2
ATE_LOOP_COUNT = 6 * BN_X + 2

# ---------------------------------------------------------------------------
# Limb configuration for on-device (JAX) representation.
#
# Field elements live on device as uint32 tensors of shape (..., N_LIMBS),
# each limb holding LIMB_BITS bits (radix 2^16).  16x16-bit limbs cover 256
# bits; products of two limbs fit in uint32, which makes schoolbook/Montgomery
# products expressible in pure uint32 vector ops (TPU VPU native width).
# ---------------------------------------------------------------------------

LIMB_BITS = 16
N_LIMBS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1

# Montgomery radix R_mont = 2^(LIMB_BITS * N_LIMBS) = 2^256.
MONT_BITS = LIMB_BITS * N_LIMBS


def to_limbs(x: int, n_limbs: int = N_LIMBS, bits: int = LIMB_BITS):
    """Little-endian limb decomposition of a Python int."""
    mask = (1 << bits) - 1
    return [(x >> (bits * i)) & mask for i in range(n_limbs)]


def from_limbs(limbs, bits: int = LIMB_BITS) -> int:
    acc = 0
    for i, limb in enumerate(limbs):
        acc |= int(limb) << (bits * i)
    return acc
