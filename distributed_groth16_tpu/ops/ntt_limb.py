"""Limb-major radix-2 NTT over BN254 Fr — the Pallas fast path for the
d_fft / h-poly pipelines (the north star names "radix-2 NTT over Fr" as a
TPU kernel; reference substrate: dist-primitives/src/dfft/mod.rs:98-182).

Layout: an Fr vector lives limb-major as uint32[16, n] (limb rows on the
sublane axis, elements on lanes), in Montgomery form, redundant [0, 2p) —
the same representation as ops/limb_kernels.LimbField, instantiated here
for the SCALAR field r (limb_kernels uses the base field q).

Structure (four-step Cooley-Tukey):
  * n <= _S_MAX: one fused Pallas kernel — bitrev in XLA, then log2(n)
    butterfly stages entirely in VMEM with per-stage twiddle tables.
  * n > _S_MAX: n = A*B split (A, B <= _S_MAX): batched NTT_A kernel over
    the B columns, one elementwise twiddle multiply w^{k1*j2} (table built
    device-side from the domain's dense root table), transpose, batched
    NTT_B kernel — output lands in natural order without a final
    permutation (X[k1 + A*k2] = Z[k2, k1] and the (16, B, A) reshape IS
    that ordering).

Differentially tested against ops/ntt.JaxDomain (itself tested against the
pure-bigint refmath.Domain).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .constants import FR_GENERATOR, R, to_limbs
from .limb_kernels import NL, LimbField, _pl, kernel_roll_mode, use_pallas
from .ntt import bitrev_perm
from .refmath import finv

# max single-kernel NTT size: the (16, S, lane-tile) block plus the stage
# temporaries must stay inside VMEM. The lane tile must be a multiple of
# 128 (Mosaic requires block minor dim % 128 == 0 — the original 64 failed
# lowering outright), so S is capped at 256: 16*256*128*4 = 2 MB per block,
# in + out + ~3 live stage temporaries ~= 10 MB of the 16 MB VMEM.
_S_MAX = 256
_LANE_TILE = 128


@functools.cache
def lfr() -> LimbField:
    """Limb-major field ops for Fr (scalar field) — LimbField is generic
    over the modulus."""
    return LimbField(R)


def _w_root(n: int) -> int:
    return pow(FR_GENERATOR, (R - 1) // n, R)


@functools.cache
def _stage_twiddles(n: int, inverse: bool) -> np.ndarray:
    """(16, logn, n//2) per-stage butterfly twiddles, Montgomery limb rows.

    Stage s (span = 2^s) uses w_{2span}^t at hi-offset t in [0, span);
    entries beyond span are padding (never read)."""
    F = lfr()
    logn = n.bit_length() - 1
    w = _w_root(n)
    if inverse:
        w = finv(w, R)
    out = np.zeros((NL, logn, max(1, n // 2)), np.uint32)
    for s in range(logn):
        span = 1 << s
        wspan = pow(w, n // (2 * span), R)
        acc = 1
        for t in range(span):
            out[:, s, t] = to_limbs(acc * F.mont_r % R)
            acc = acc * wspan % R
    return out


def _ntt_body(x, tw, p_col, p2_col, logn: int, unroll: bool):
    """x: (16, S, L) bitrev-ordered; returns natural-order NTT along axis 1.
    All reshapes static; every field op flattens to (16, -1) 2D."""
    F = lfr()
    S, L = x.shape[1], x.shape[2]

    def fl(a):
        return a.reshape(NL, -1)

    for s in range(logn):
        span = 1 << s
        blocks = S // (2 * span)
        xr = x.reshape(NL, blocks, 2, span, L)
        lo, hi = xr[:, :, 0], xr[:, :, 1]  # (16, blocks, span, L)
        tws = jax.lax.slice_in_dim(tw, s, s + 1, axis=1)  # (16, 1, n//2)
        tws = jax.lax.slice_in_dim(tws, 0, span, axis=2)  # (16, 1, span)
        twb = jnp.broadcast_to(
            tws[:, :, None, :, None], (NL, 1, blocks, span, L)
        ).reshape(NL, blocks, span, L)
        t = F.mul(fl(hi), fl(twb), p_col, unroll).reshape(hi.shape)
        nlo = F.add(fl(lo), fl(t), p2_col, unroll).reshape(lo.shape)
        nhi = F.sub(fl(lo), fl(t), p2_col, unroll).reshape(lo.shape)
        x = jnp.stack([nlo, nhi], axis=2).reshape(NL, S, L)
    return x


class _SmallNTT:
    """Compiled size-S NTT (transform on axis 1, batch on axis 2)."""

    def __init__(self, S: int, inverse: bool):
        self.S = S
        self.logn = S.bit_length() - 1
        self.inverse = inverse
        self.tw_np = _stage_twiddles(S, inverse)
        # numpy, NOT jnp: __init__ may run inside a jit trace (functools
        # cache of _small), and jnp.asarray there yields a tracer that
        # poisons every later call
        self.perm = bitrev_perm(S)

    @functools.cached_property
    def _xla(self):
        F = lfr()

        @jax.jit
        def run(x):  # (16, S, L) natural order
            x = jnp.take(x, self.perm, axis=1)
            return _ntt_body(
                x, jnp.asarray(self.tw_np), jnp.asarray(F.p_col),
                jnp.asarray(F.p2_col), self.logn, unroll=False,
            )

        return run

    @functools.cached_property
    def _pallas(self):
        pl, pltpu = _pl()
        F = lfr()
        S, logn = self.S, self.logn
        TW = self.tw_np.shape[2]

        def kern(x_ref, tw_ref, c_ref, o_ref):
            consts = c_ref[:]
            o_ref[:] = _ntt_body(
                x_ref[:], tw_ref[:], consts[0:NL], consts[NL:],
                logn, unroll=kernel_roll_mode(),
            )

        consts = np.concatenate([F.p_col, F.p2_col], axis=0)

        @jax.jit
        def run(x):  # (16, S, L) natural order
            x = jnp.take(x, self.perm, axis=1)
            L = x.shape[2]
            lt = min(_LANE_TILE, L)
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((NL, S, L), jnp.uint32),
                grid=(L // lt,),
                in_specs=[
                    pl.BlockSpec((NL, S, lt), lambda i: (0, 0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((NL, logn, TW), lambda i: (0, 0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((2 * NL, 1), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((NL, S, lt), lambda i: (0, 0, i),
                                       memory_space=pltpu.VMEM),
            )(x, jnp.asarray(self.tw_np), jnp.asarray(consts))

        return run

    def __call__(self, x):
        """(16, S, L) natural-order columns -> NTT'd along axis 1."""
        L = x.shape[2]
        if use_pallas() and L % _LANE_TILE == 0:
            return self._pallas(x)
        return self._xla(x)


@functools.cache
def _small(S: int, inverse: bool) -> _SmallNTT:
    return _SmallNTT(S, inverse)


def _wpows_lm_traced(n: int, inverse: bool):
    """(16, n) limb-major Montgomery table of w^0..w^{n-1}, built with
    O(log n) TRACED device muls — deliberately not a host-side constant.

    The previous formulation cached a host numpy table and let jit embed
    it: at n = 2^20 that baked a 64 MB literal into the program (135 MB of
    StableHLO total), which is exactly the kind of monolith that wedged
    the remote Mosaic service. Building it in-trace costs ~log2(n) muls of
    (16, n) at runtime — negligible against the transform itself in the
    prover, and XLA CSE dedups the rebuild across back-to-back transforms
    in one program (the tables are pure functions of constants). Output is
    redundant [0, 2p), a valid mul operand downstream."""
    F = lfr()
    w = _w_root(n)
    if inverse:
        w = finv(w, R)
    logn = max(1, (n - 1).bit_length())
    k = jnp.arange(n, dtype=jnp.uint32)
    one = np.array(to_limbs(F.mont_r), np.uint32).reshape(NL, 1)
    tbl = jnp.broadcast_to(jnp.asarray(one), (NL, n))
    p_col = jnp.asarray(F.p_col)
    for b in range(logn):
        wb = np.array(
            to_limbs(pow(w, 1 << b, R) * F.mont_r % R), np.uint32
        ).reshape(NL, 1)
        hit = ((k >> b) & 1) == 1
        tbl = jnp.where(
            hit[None, :],
            F.mul(tbl, jnp.asarray(wb), p_col, unroll=False),
            tbl,
        )
    return tbl


def _ntt_rec(x, n: int, inverse: bool, L: int):
    """(16, n, L) batched NTT along axis 1, natural order in/out.

    Recursion: n = A*B with A = min(n, _S_MAX); NTT_A batched over (B, L),
    per-level twiddle w_n^{k1*j2}, transpose, recurse on B batched over
    (A, L). Output ordering X[k1 + A*k2] = Z[k2, k1] makes the final
    reshape natural order with no extra permutation."""
    F = lfr()
    if n <= _S_MAX:
        return _small(n, inverse)(x)
    A = _S_MAX
    B = n // A
    m = x.reshape(NL, A, B * L)
    y = _small(A, inverse)(m).reshape(NL, A, B, L)
    # twiddle w^{k1*j2}: indices into this level's dense root table mod n
    k1 = jnp.arange(A, dtype=jnp.uint32)[:, None]
    j2 = jnp.arange(B, dtype=jnp.uint32)[None, :]
    idx = (k1 * j2) % jnp.uint32(n)  # (A, B)
    wp = _wpows_lm_traced(n, inverse)  # (16, n)
    tw = jnp.take(wp, idx.reshape(-1), axis=1).reshape(NL, A, B, 1)
    y = F.mul(
        y.reshape(NL, -1),
        jnp.broadcast_to(tw, y.shape).reshape(NL, -1),
        jnp.asarray(F.p_col),
        unroll=False,
    ).reshape(NL, A, B, L)
    z = _ntt_rec(
        jnp.transpose(y, (0, 2, 1, 3)).reshape(NL, B, A * L), B, inverse,
        A * L,
    )
    return z.reshape(NL, n, L)


@functools.partial(jax.jit, static_argnums=(1, 2))
def ntt_limb(x, n: int, inverse: bool = False):
    """Full-size NTT: x (16, n) Montgomery limb-major, natural order in and
    out. No 1/n scaling on inverse (caller applies size_inv, matching the
    JaxDomain decomposition of ifft)."""
    return _ntt_rec(x[:, :, None], n, inverse, 1)[:, :, 0]


# -- row-major convenience wrappers (differential-test surface) -------------


@functools.partial(jax.jit, static_argnums=(1, 2))
def fft_rm(coeffs_rm, n: int, inverse: bool = False):
    """(n, 16) row-major Montgomery -> (n, 16); canonical output."""
    F = lfr()
    x = jnp.transpose(coeffs_rm)
    out = ntt_limb(x, n, inverse)
    if inverse:
        size_inv = jnp.asarray(
            np.array(to_limbs(finv(n, R) * F.mont_r % R), np.uint32)
        ).reshape(NL, 1)
        out = F.mul(out, size_inv, jnp.asarray(F.p_col), unroll=False)
    return jnp.transpose(F.canon(out))
