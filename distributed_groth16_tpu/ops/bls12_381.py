"""BLS12-381 G1/G2 — the third curve configuration (BASELINE config 5:
G1/G2 MSM at 2^24 points with packed secret sharing).

As with ops/bls12_377.py, every constant is DERIVED from the BLS seed at
import and self-checked, so nothing is copied on trust:

    x  = -0xD201000000010000                     (the BLS parameter, negative)
    r  = x^4 - x^2 + 1                           (scalar field, 255 bits,
                                                  two-adicity 32 — 2^24 NTT
                                                  domains fit comfortably)
    q  = ((x - 1)^2 * r) / 3 + x                 (base field, 381 bits)
    G1 : y^2 = x^3 + 4         over Fq,  cofactor (x-1)^2 / 3
    G2 : y^2 = x^3 + 4(1 + u)  over Fq2 = Fq[u]/(u^2+1)

Base-field elements use 24x16-bit limbs (PrimeField is limb-count
generic); Fr381 Montgomery elements take 17 limbs (radix 2^272 — the
255-bit r needs 4p < radix headroom) while STANDARD-form scalars still
fit the 16-limb/256-bit layout the MSM digit machinery consumes (d_msm
slices the zero top limb). Generators follow this
package's deterministic smallest-x convention (generator choice is a
convention, not part of the group). The limb-major Pallas tree kernels
remain BN254-only for now (16-limb layout); this curve rides the generic
row-major path.
"""

from __future__ import annotations

import functools

from . import refmath as rm
from .primemath import (
    factor,
    fq2_inv,
    fq2_mul,
    is_probable_prime,
    smallest_generator,
    sqrt_mod,
)

# --------------------------------------------------------------------------
# parameter derivation from the seed
# --------------------------------------------------------------------------

X = -0xD201000000010000
R381 = X**4 - X**2 + 1
Q381 = ((X - 1) ** 2 * R381) // 3 + X
G1_B381 = 4
G2_B381 = (4, 4)  # 4 * (1 + u)
G1_COFACTOR = (X - 1) ** 2 // 3
# standard G2 cofactor: (x^8 - 4x^7 + 5x^6 - 4x^4 + 6x^3 - 4x^2 - 4x + 13)/9
G2_COFACTOR = (
    X**8 - 4 * X**7 + 5 * X**6 - 4 * X**4 + 6 * X**3 - 4 * X**2 - 4 * X + 13
) // 9

FR_TWO_ADICITY_381 = ((R381 - 1) & -(R381 - 1)).bit_length() - 1  # = 32


@functools.cache
def _fr_generator() -> int:
    """Smallest multiplicative generator of Fr381: r-1 = x^2 (x-1)(x+1)
    factors through |x|-sized integers."""
    return smallest_generator(
        R381, factor(-X) | factor(abs(X - 1)) | factor(abs(X + 1))
    )


# --------------------------------------------------------------------------
# self-checks (import-time; cheap)
# --------------------------------------------------------------------------

assert R381.bit_length() == 255 and Q381.bit_length() == 381
assert ((X - 1) ** 2 * R381) % 3 == 0, "q derivation divisibility"
assert is_probable_prime(R381), "r not prime"
assert is_probable_prime(Q381), "q not prime"
assert Q381 % 4 == 3, "fast sqrt + u^2=-1 tower assumption"
# curve/group consistency: #E(Fq) = h * r = q + 1 - t with t = x + 1
assert G1_COFACTOR * R381 == Q381 + 1 - (X + 1), "Hasse/trace identity"
assert (R381 - 1) % (1 << FR_TWO_ADICITY_381) == 0
assert FR_TWO_ADICITY_381 >= 25, "2^24 product domains must fit"


# --------------------------------------------------------------------------
# host ground truth
# --------------------------------------------------------------------------

G1_HOST = rm._CurveOps(
    add=lambda a, b: (a + b) % Q381,
    sub=lambda a, b: (a - b) % Q381,
    mul=lambda a, b: a * b % Q381,
    sq=lambda a: a * a % Q381,
    neg=lambda a: (-a) % Q381,
    inv=lambda a: rm.finv(a, Q381),
    scalar=lambda a, k: a * k % Q381,
    zero=0,
    one=1,
    b=G1_B381,
    order=R381,
)


def _f2_add(a, b):
    return ((a[0] + b[0]) % Q381, (a[1] + b[1]) % Q381)


def _f2_sub(a, b):
    return ((a[0] - b[0]) % Q381, (a[1] - b[1]) % Q381)


def _f2_mul(a, b):
    return fq2_mul(a, b, Q381)


def _f2_inv(a):
    return fq2_inv(a, Q381)


G2_HOST = rm._CurveOps(
    add=_f2_add,
    sub=_f2_sub,
    mul=_f2_mul,
    sq=lambda a: _f2_mul(a, a),
    neg=lambda a: ((-a[0]) % Q381, (-a[1]) % Q381),
    inv=_f2_inv,
    scalar=lambda a, k: (a[0] * k % Q381, a[1] * k % Q381),
    zero=(0, 0),
    one=(1, 0),
    b=G2_B381,
    order=R381,
)


def _sqrt_fq2(a):
    """Square root in Fq2 = Fq[u]/(u^2+1) (q ≡ 3 mod 4 method)."""
    a0, a1 = a[0] % Q381, a[1] % Q381
    if a1 == 0:
        s = sqrt_mod(a0, Q381)
        if s is not None:
            return (s, 0)
        # a0 is a non-residue: sqrt is purely imaginary, (0, t) with
        # t^2 = -a0
        t = sqrt_mod((-a0) % Q381, Q381)
        return None if t is None else (0, t)
    n = sqrt_mod((a0 * a0 + a1 * a1) % Q381, Q381)
    if n is None:
        return None
    inv2 = rm.finv(2, Q381)
    for sign in (1, -1):
        x0sq = (a0 + sign * n) % Q381 * inv2 % Q381
        x0 = sqrt_mod(x0sq, Q381)
        if x0 is not None and x0 != 0:
            x1 = a1 * rm.finv(2 * x0 % Q381, Q381) % Q381
            if _f2_mul((x0, x1), (x0, x1)) == (a0, a1):
                return (x0, x1)
    return None


@functools.cache
def g1_generator_381() -> tuple[int, int]:
    """Deterministic G1 generator: smallest x with x^3 + 4 square, smaller
    root, cofactor-cleared into the r-torsion."""
    gx = 0
    while True:
        rhs = (gx * gx * gx + G1_B381) % Q381
        y = sqrt_mod(rhs, Q381)
        if y is not None:
            pt = G1_HOST.scalar_mul((gx, min(y, Q381 - y)), G1_COFACTOR)
            if pt is not None:
                assert G1_HOST.is_on_curve(pt)
                assert G1_HOST.scalar_mul(pt, R381) is None, "not r-torsion"
                return pt
        gx += 1


@functools.cache
def g2_generator_381():
    """Deterministic G2 generator: smallest x = (k, 1) with a square RHS,
    cofactor-cleared into the r-torsion."""
    k = 0
    while True:
        x = (k, 1)
        rhs = _f2_add(_f2_mul(_f2_mul(x, x), x), G2_B381)
        y = _sqrt_fq2(rhs)
        if y is not None:
            pt = G2_HOST.scalar_mul((x, y), G2_COFACTOR)
            if pt is not None:
                assert G2_HOST.is_on_curve(pt)
                assert G2_HOST.scalar_mul(pt, R381) is None, "not r-torsion"
                return pt
        k += 1


# --------------------------------------------------------------------------
# device instances
# --------------------------------------------------------------------------


@functools.cache
def fq381():
    from .field import PrimeField

    return PrimeField(Q381)  # 24 limbs, Montgomery radix 2^384


@functools.cache
def fr381():
    from .field import PrimeField

    return PrimeField(R381)  # 17 limbs (radix 2^272): 255-bit r needs
    # 4p < radix; STANDARD-form scalars still fit 16 limbs (dmsm slices)


@functools.cache
def fq2_381():
    from .field import Fq2Ops

    return Fq2Ops(fq381())  # u^2 = -1 tower (Q381 ≡ 3 mod 4)


@functools.cache
def g1_381():
    from .curve import CurvePoints

    nl = fq381().nl
    return CurvePoints(fq381(), G1_B381, (nl,), scalar_order=R381)


@functools.cache
def g2_381():
    from .curve import CurvePoints

    nl = fq381().nl
    return CurvePoints(fq2_381(), G2_B381, (2, nl), scalar_order=R381)


def encode_scalars_381(values):
    """Python ints -> (n, 16) standard-form u32 limbs mod r381."""
    from .scalar_pack import encode_scalars

    return encode_scalars(values, R381)


@functools.cache
def pss381(l: int):
    """PackedSharingParams over the BLS12-381 scalar field (host domains +
    in-the-exponent maps; device field-share transforms raise — see
    pss377's docstring for the split)."""
    from ..parallel.pss import PackedSharingParams

    return PackedSharingParams(l, modulus=R381, generator=_fr_generator())


def pack_scalars_381(pp, values):
    """Pack Fr381 secrets into n Montgomery shares (scalar_pack.pack_scalars
    over PrimeField(R381), nl=17; CONSECUTIVE chunking)."""
    from .scalar_pack import pack_scalars

    return pack_scalars(pp, values, fr381(), R381)
