"""GLV scalar decomposition for BN254 G1 — host-side precomputation.

The in-the-exponent PSS transforms (parallel/pss.py) and the point-domain
NTT apply FIXED Fr scalars to runtime curve points. A straight double-and-
add ladder costs 256 sequential point-add rounds; BN254 G1 carries the GLV
endomorphism phi(x, y) = (beta*x, y) with phi(P) = lambda*P (beta a cube
root of unity in Fq, lambda the matching cube root of unity mod r), so any
scalar k splits as k = k1 + k2*lambda with |k1|, |k2| ~ sqrt(r) ~ 2^128.
The ladder then runs over the doubled base set {P, phi(P)} at HALF the
sequential depth — the dominant latency of every unpackexp king step.

All of this is host-side integer math executed once per (matrix, domain);
nothing here runs on device. The reference delegates the same role to
arkworks' glv-lattice-basis precomputation inside ark-ec (consumed via
G::msm in dist-primitives/src/dmsm/mod.rs:82); here the decomposition is
derived from first principles (Tonelli–Shanks for the cube roots, the
classic GLV extended-Euclid lattice basis) and verified against the host
curve at import time.
"""

from __future__ import annotations

import functools
import math

from .constants import G1_GENERATOR, Q, R


def sqrt_mod(a: int, p: int) -> int | None:
    """Tonelli–Shanks square root mod an odd prime p (None if non-residue)."""
    a %= p
    if a == 0:
        return 0
    if pow(a, (p - 1) // 2, p) != 1:
        return None
    if p % 4 == 3:
        return pow(a, (p + 1) // 4, p)
    # write p-1 = q * 2^s with q odd
    q, s = p - 1, 0
    while q % 2 == 0:
        q //= 2
        s += 1
    # find a non-residue z
    z = 2
    while pow(z, (p - 1) // 2, p) != p - 1:
        z += 1
    m, c, t, r_ = s, pow(z, q, p), pow(a, q, p), pow(a, (q + 1) // 2, p)
    while t != 1:
        # find least i with t^(2^i) = 1
        i, t2 = 0, t
        while t2 != 1:
            t2 = t2 * t2 % p
            i += 1
        b = pow(c, 1 << (m - i - 1), p)
        m, c = i, b * b % p
        t, r_ = t * c % p, r_ * b % p
    return r_


def _cube_roots_of_unity(p: int) -> tuple[int, int]:
    """The two primitive cube roots of unity mod p (roots of x^2 + x + 1)."""
    s = sqrt_mod(p - 3, p)
    assert s is not None, "p = 1 mod 3 required"
    inv2 = pow(2, p - 2, p)
    r1 = (s - 1) * inv2 % p
    r2 = (-s - 1) * inv2 % p
    for r_ in (r1, r2):
        assert (r_ * r_ + r_ + 1) % p == 0
    return r1, r2


def _glv_basis(n: int, lam: int) -> tuple[tuple[int, int], tuple[int, int]]:
    """Two short vectors spanning the lattice {(x, y) : x + y*lam = 0 mod n}.

    Classic GLV (Gallant–Lambert–Vanstone 2001) half-GCD construction: run
    the extended Euclidean algorithm on (n, lam); every remainder r_i
    satisfies r_i = s_i*n + t_i*lam, i.e. (r_i, -t_i) is a lattice vector;
    stop around sqrt(n) where both components are ~sqrt(n)."""
    sqrt_n = math.isqrt(n)
    rs = [n, lam]
    ts = [0, 1]
    while rs[-1] != 0:
        q_ = rs[-2] // rs[-1]
        rs.append(rs[-2] - q_ * rs[-1])
        ts.append(ts[-2] - q_ * ts[-1])
    # index l: last remainder >= sqrt(n)
    l_idx = max(i for i, r_ in enumerate(rs) if r_ >= sqrt_n)
    v1 = (rs[l_idx + 1], -ts[l_idx + 1])
    c1 = (rs[l_idx], -ts[l_idx])
    c2 = (rs[l_idx + 2], -ts[l_idx + 2]) if l_idx + 2 < len(rs) else c1
    v2 = c1 if c1[0] ** 2 + c1[1] ** 2 <= c2[0] ** 2 + c2[1] ** 2 else c2
    for a, b in (v1, v2):
        assert (a + b * lam) % n == 0
    return v1, v2


class GlvParams:
    """Decomposition parameters for one (modulus, lambda, beta) triple."""

    def __init__(self, n: int, lam: int, beta: int):
        self.n = n
        self.lam = lam
        self.beta = beta
        self.v1, self.v2 = _glv_basis(n, lam)
        # max bit length of a decomposed half (+1 safety): ladder trip count
        self.max_bits = max(abs(c).bit_length() for c in self.v1 + self.v2) + 2

    def decompose(self, k: int) -> tuple[int, int]:
        """k -> (k1, k2) with k1 + k2*lam = k (mod n), |ki| < 2^max_bits.

        Babai round-off against the lattice basis: (k, 0) - c1*v1 - c2*v2
        with ci the nearest-integer coefficients of (k, 0) in the basis."""
        k %= self.n
        (a1, b1), (a2, b2) = self.v1, self.v2
        det = a1 * b2 - a2 * b1
        # (k,0) = x*v1 + y*v2 with x = k*b2/det, y = -k*b1/det
        c1 = _round_div(k * b2, det)
        c2 = _round_div(-k * b1, det)
        k1 = k - c1 * a1 - c2 * a2
        k2 = -c1 * b1 - c2 * b2
        assert (k1 + k2 * self.lam - k) % self.n == 0
        assert abs(k1).bit_length() <= self.max_bits
        assert abs(k2).bit_length() <= self.max_bits
        return k1, k2


def _round_div(a: int, b: int) -> int:
    """Nearest-integer division (ties toward +inf), exact for big ints."""
    if b < 0:
        a, b = -a, -b
    return (2 * a + b) // (2 * b)


@functools.cache
def bn254_g1_glv() -> GlvParams:
    """GLV parameters for BN254 G1, with the (beta, lambda) pairing verified
    against the host curve: (beta*x, y) == lambda * (x, y) on the generator."""
    from . import refmath as rm

    lams = _cube_roots_of_unity(R)
    betas = _cube_roots_of_unity(Q)
    gx, gy = G1_GENERATOR
    for lam in lams:
        target = rm.G1.scalar_mul((gx, gy), lam)
        for beta in betas:
            if target == (beta * gx % Q, gy):
                return GlvParams(R, lam, beta)
    raise AssertionError("no (beta, lambda) pair matched on the generator")
