"""Host-side BN254 optimal ate pairing — the Groth16 verification oracle.

Verification is not the workload (the reference verifies through arkworks'
pairing, groth16/examples/sha256.rs:228-254); proofs are seconds of TPU
compute, the pairing check is milliseconds of host bigint math. This module
is therefore deliberately pure Python: simple, auditable, and the ground
truth our device-side prover is differentially tested against.

Tower: Fq2 = Fq[u]/(u^2+1) (ops/refmath.py), Fq12 = Fq2[w]/(w^6 - xi) with
xi = 9 + u (the D-type twist constant, ops/constants.py). G2 points live on
the twist E'(Fq2): y^2 = x^3 + b/xi; the untwist embedding into E(Fq12) is
(x, y) -> (x w^2, y w^3), which is where the sparse line-function shape
below comes from.
"""

from __future__ import annotations

from .constants import ATE_LOOP_COUNT, FQ2_NON_RESIDUE, Q, R
from .refmath import (
    FQ2_ONE,
    FQ2_ZERO,
    fq2_add,
    fq2_conj,
    fq2_inv,
    fq2_mul,
    fq2_neg,
    fq2_scalar,
    fq2_sq,
    fq2_sub,
    G2,
)

# ---------------------------------------------------------------------------
# Fq12 = Fq2[w]/(w^6 - xi): elements are 6-tuples of Fq2 coefficients
# (c0 + c1 w + ... + c5 w^5).
# ---------------------------------------------------------------------------

FQ12_ONE = (FQ2_ONE,) + (FQ2_ZERO,) * 5
FQ12_ZERO = (FQ2_ZERO,) * 6

_XI = FQ2_NON_RESIDUE


def fq12_mul(a, b):
    # schoolbook over w, then fold w^(6+k) = xi * w^k
    acc = [FQ2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == FQ2_ZERO:
            continue
        for j in range(6):
            if b[j] == FQ2_ZERO:
                continue
            acc[i + j] = fq2_add(acc[i + j], fq2_mul(ai, b[j]))
    out = list(acc[:6])
    for k in range(5):
        out[k] = fq2_add(out[k], fq2_mul(acc[6 + k], _XI))
    return tuple(out)


def fq12_sq(a):
    return fq12_mul(a, a)


def fq12_conj(a):
    """Conjugation by w -> -w (the q^6 Frobenius): negate odd coefficients."""
    return (a[0], fq2_neg(a[1]), a[2], fq2_neg(a[3]), a[4], fq2_neg(a[5]))


def fq12_pow(a, e: int):
    acc, base = FQ12_ONE, a
    while e:
        if e & 1:
            acc = fq12_mul(acc, base)
        base = fq12_sq(base)
        e >>= 1
    return acc


# ---------------------------------------------------------------------------
# Line functions (affine, on the twist) — sparse Fq12 elements.
#
# Untwisted line through psi(T) evaluated at P = (xp, yp) in G1:
#     l = yp  -  (lambda * xp) w  +  (lambda * x_T - y_T) w^3
# with lambda the affine slope on the twist (an Fq2 element).
# ---------------------------------------------------------------------------


def _line(slope, x_t, y_t, xp: int, yp: int):
    c0 = (yp % Q, 0)
    c1 = fq2_neg(fq2_scalar(slope, xp))
    c3 = fq2_sub(fq2_mul(slope, x_t), y_t)
    return (c0, c1, FQ2_ZERO, c3, FQ2_ZERO, FQ2_ZERO)


def _dbl_step(t, p):
    """Returns (2T, line_{T,T}(P)). T = (x, y) affine on the twist."""
    x, y = t
    slope = fq2_mul(fq2_scalar(fq2_sq(x), 3), fq2_inv(fq2_scalar(y, 2)))
    x3 = fq2_sub(fq2_sq(slope), fq2_scalar(x, 2))
    y3 = fq2_sub(fq2_mul(slope, fq2_sub(x, x3)), y)
    return (x3, y3), _line(slope, x, y, p[0], p[1])


def _add_step(t, q, p):
    """Returns (T+Q, line_{T,Q}(P))."""
    x1, y1 = t
    x2, y2 = q
    slope = fq2_mul(fq2_sub(y2, y1), fq2_inv(fq2_sub(x2, x1)))
    x3 = fq2_sub(fq2_sub(fq2_sq(slope), x1), x2)
    y3 = fq2_sub(fq2_mul(slope, fq2_sub(x1, x3)), y1)
    return (x3, y3), _line(slope, x1, y1, p[0], p[1])


# Frobenius on the twist: pi(x, y) = (gamma12 * conj(x), gamma13 * conj(y)),
# gamma12 = xi^((q-1)/3), gamma13 = xi^((q-1)/2).
def _fq2_pow(a, e: int):
    acc, base = FQ2_ONE, a
    while e:
        if e & 1:
            acc = fq2_mul(acc, base)
        base = fq2_sq(base)
        e >>= 1
    return acc


_GAMMA12 = _fq2_pow(_XI, (Q - 1) // 3)
_GAMMA13 = _fq2_pow(_XI, (Q - 1) // 2)


def _frob_twist(t):
    x, y = t
    return (fq2_mul(_GAMMA12, fq2_conj(x)), fq2_mul(_GAMMA13, fq2_conj(y)))


def miller_loop(q2, p1):
    """Miller loop f_{6x+2, Q}(P) for Q on the twist (affine Fq2 pair) and
    P in G1 (affine int pair). Either None (infinity) gives f = 1."""
    if q2 is None or p1 is None:
        return FQ12_ONE
    f = FQ12_ONE
    t = q2
    for bit in bin(ATE_LOOP_COUNT)[3:]:
        t, l = _dbl_step(t, p1)
        f = fq12_mul(fq12_sq(f), l)
        if bit == "1":
            t, l = _add_step(t, q2, p1)
            f = fq12_mul(f, l)
    # the two Frobenius correction steps of the optimal ate pairing
    q1 = _frob_twist(q2)
    nq2 = _frob_twist(q1)
    nq2 = (nq2[0], fq2_neg(nq2[1]))
    t, l = _add_step(t, q1, p1)
    f = fq12_mul(f, l)
    _, l = _add_step(t, nq2, p1)
    f = fq12_mul(f, l)
    return f


_FINAL_EXP = (Q**12 - 1) // R


def final_exponentiation(f):
    """f^((q^12-1)/r). Easy part via conjugation/inversion-free identity is
    skipped — one big pow keeps this obviously correct; verification is
    host-side and rare."""
    return fq12_pow(f, _FINAL_EXP)


def pairing(q2, p1):
    """e(P, Q) with P in G1 (affine int pair or None), Q in G2 (affine Fq2
    pair or None). Returns an Fq12 element."""
    return final_exponentiation(miller_loop(q2, p1))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i) via one shared final exponentiation.

    pairs: iterable of (q2, p1). The product of Miller loops is finalized
    once — the standard batched-verification trick.
    """
    f = FQ12_ONE
    for q2, p1 in pairs:
        f = fq12_mul(f, miller_loop(q2, p1))
    return final_exponentiation(f)


def pairing_check(pairs) -> bool:
    """True iff prod_i e(P_i, Q_i) == 1."""
    return multi_pairing(pairs) == FQ12_ONE


__all__ = [
    "FQ12_ONE",
    "fq12_mul",
    "fq12_pow",
    "miller_loop",
    "final_exponentiation",
    "pairing",
    "multi_pairing",
    "pairing_check",
    "G2",
]
