"""Radix-2 NTT over BN254 Fr for JAX/TPU, matching ark-poly's
Radix2EvaluationDomain semantics (the reference's FFT substrate for both packed
secret sharing — secret-sharing/src/pss.rs:39-47 — and the distributed FFT,
dist-primitives/src/dfft/mod.rs).

A `JaxDomain(size, offset)` evaluates polynomials at offset * w^i where
w = g^((r-1)/size), g = 5 (arkworks Fr::GENERATOR). Data layout: coefficient /
evaluation vectors are (..., n, 16) uint32 Montgomery limb tensors.

XLA-friendliness: the transform is a single shape-uniform butterfly body run
under `lax.fori_loop` over the log2(n) stages — twiddles are looked up from one
dense table of the n-th roots of unity by index arithmetic — so the compiled
graph size is independent of n and a domain of any size reuses one compiled
butterfly per batch shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import metrics as _tm
from .constants import FR_GENERATOR, FR_TWO_ADICITY, N_LIMBS, R
from .field import fr
from .refmath import finv

# same family ops/msm.py registers (idempotent): which NTT path ran —
# dashboards catch a TPU backend silently on the row-major fallback
_ROUTE = _tm.registry().counter(
    "kernel_route_total",
    "Kernel-path routing decisions at dispatch/trace time, per kernel "
    "and chosen implementation path",
    ("kernel", "path"),
)
_R_LIMB = _ROUTE.labels(kernel="ntt", path="limb")
_R_ROW = _ROUTE.labels(kernel="ntt", path="row")


def _tracing_active() -> bool:
    """True when called under a jit/vmap trace. Prefers the private
    trace_state_clean (public jax.core lost it in this version); if a
    future jax drops the _src alias too, falls back to probing whether
    arithmetic on a concrete array yields a Tracer — and on any probe
    failure conservatively reports True (the in-trace path is always
    correct, just slightly more device work)."""
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:
        try:
            probe = jnp.zeros((), dtype=jnp.int32) + 0
            return isinstance(probe, jax.core.Tracer)
        except Exception:
            return True


def _bitrev(n: int, xp):
    """Bit-reversal permutation over array namespace xp (np for the host
    table, jnp for in-trace builds — one implementation for both paths)."""
    assert n > 0 and n & (n - 1) == 0, f"bitrev needs a power of two, got {n}"
    logn = n.bit_length() - 1
    idx = xp.arange(n, dtype=xp.int32)
    out = xp.zeros((n,), dtype=xp.int32)
    for b in range(logn):
        out = out | (((idx >> b) & 1) << (logn - 1 - b))
    return out


def bitrev_perm(n: int) -> np.ndarray:
    """Bit-reversal permutation indices (matches dfft/mod.rs:258-271)."""
    return _bitrev(n, np)


@functools.partial(jax.jit, static_argnames=("logn", "inverse"))
def _ntt_core(x, perm, wpows, logn: int, inverse: bool = False):
    """DIT radix-2 NTT with dense root table.

    x:     (..., n, 16) Montgomery uint32
    perm:  (n,) int32 bit-reversal permutation
    wpows: (n, 16) Montgomery powers w^0..w^{n-1} of the size-n FORWARD root;
           the inverse transform indexes it as w^{-k} = wpows[(n-k) mod n].
    """
    F = fr()
    n = x.shape[-2]
    x = jnp.take(x, perm, axis=-2)
    j = jnp.arange(n, dtype=jnp.int32)

    def stage(s, x):
        span = jnp.int32(1) << s
        # butterfly partners: lo has bit s clear, hi has bit s set
        lo_idx = j & ~span
        hi_idx = j | span
        # twiddle for lane j: wspan^(j mod span) with wspan = w^(n/(2*span))
        k = (j & (span - 1)) * (jnp.int32(n) >> (s + 1))
        if inverse:
            k = (jnp.int32(n) - k) & jnp.int32(n - 1)
        w = jnp.take(wpows, k, axis=0)
        lo = jnp.take(x, lo_idx, axis=-2)
        hi = jnp.take(x, hi_idx, axis=-2)
        t = F.mul(hi, w)
        is_lo = (j & span) == 0
        return jnp.where(is_lo[:, None], F.add(lo, t), F.sub(lo, t))

    return jax.lax.fori_loop(0, logn, stage, x)


class JaxDomain:
    """Device-side radix-2 evaluation domain over Fr (ark semantics)."""

    def __init__(self, size: int, offset: int = 1):
        assert size & (size - 1) == 0 and size > 0
        assert size <= (1 << FR_TWO_ADICITY)
        self.size = size
        self.logn = size.bit_length() - 1
        self.offset = offset % R
        self.group_gen = pow(FR_GENERATOR, (R - 1) // size, R)
        self.group_gen_inv = finv(self.group_gen, R)
        F = fr()
        # NUMPY, not jnp: domain() is functools-cached, and the first
        # construction may happen inside a jit trace — jnp.asarray under
        # an active trace yields a tracer-backed constant that would be
        # cached and poison every later eager fft/ifft. numpy arrays are
        # plain constants in both worlds (jnp.take accepts numpy indices;
        # F.mul accepts a numpy operand).
        self._perm = bitrev_perm(size)
        self._size_inv = F.encode_np([finv(size, R)])[0]
        # The device root/offset tables are built LAZILY, first time they
        # are needed outside a trace (_live_* below): domain() is
        # functools.cached, and if the first construction happened inside a
        # jit trace an eager _powers_device here would cache TRACERS that
        # poison every later call (the _SmallNTT "numpy, NOT jnp" lesson).
        self._wpows_cached = None
        self._perm_cached = None
        self._off_cached: dict[bool, jnp.ndarray] = {}

    def elements(self) -> list[int]:
        out, acc = [], self.offset
        for _ in range(self.size):
            out.append(acc)
            acc = acc * self.group_gen % R
        return out

    # -- trace-aware table access -------------------------------------------
    # Under an active trace the precomputed device tables would be captured
    # as jit CONSTANTS and baked into the lowered module as literals — at
    # n = 2^20 that is a 64 MB literal PER TABLE (observed: 135 MB of
    # StableHLO for one transform), the exact monolith class that wedged
    # the remote TPU compile service. Rebuilding in-trace costs O(log n)
    # muls of device work and keeps programs small; eager callers keep the
    # cached concrete tables.

    def _live_wpows(self):
        if _tracing_active():
            return _powers_device(self.group_gen, self.size)
        if self._wpows_cached is None:
            self._wpows_cached = _powers_device(self.group_gen, self.size)
        return self._wpows_cached

    def _live_perm(self):
        if not _tracing_active():
            if self._perm_cached is None:
                self._perm_cached = jnp.asarray(self._perm)
            return self._perm_cached
        return _bitrev_traced(self.size)

    def _live_off(self, inverse: bool):
        if self.offset == 1:
            return None
        base = finv(self.offset, R) if inverse else self.offset
        if _tracing_active():
            return _powers_device(base, self.size)
        if inverse not in self._off_cached:
            self._off_cached[inverse] = _powers_device(base, self.size)
        return self._off_cached[inverse]

    def fft(self, coeffs):
        """Evaluate: (..., k<=n, 16) coeffs -> (..., n, 16) evals."""
        F = fr()
        x = _zpad(coeffs, self.size)
        off = self._live_off(False)
        if off is not None:
            x = F.mul(x, off)
        if _limb_ntt_ok(self.size):
            _R_LIMB.inc()
            return _limb_ntt_route(x, self.size, False)
        _R_ROW.inc()
        return _ntt_core(x, self._live_perm(), self._live_wpows(), self.logn)

    def ifft(self, evals):
        """Interpolate: (..., k<=n, 16) evals -> (..., n, 16) coeffs."""
        F = fr()
        x = _zpad(evals, self.size)
        if _limb_ntt_ok(self.size):
            _R_LIMB.inc()
            x = _limb_ntt_route(x, self.size, True)
        else:
            _R_ROW.inc()
            x = _ntt_core(
                x, self._live_perm(), self._live_wpows(), self.logn,
                inverse=True,
            )
        x = F.mul(x, self._size_inv)
        off = self._live_off(True)
        if off is not None:
            x = F.mul(x, off)
        return x

    def get_coset(self, offset: int) -> "JaxDomain":
        return domain(self.size, offset * self.offset % R)


def _limb_ntt_ok(n: int) -> bool:
    """Route big transforms to the limb-major Pallas path (ops/ntt_limb.py)
    on TPU backends, or anywhere under DG16_FORCE_LIMB_NTT=1 (differential
    tests exercise the identical XLA bodies on CPU). Small transforms keep
    the row-major fori core: the limb path's layout transposes only pay
    off when the butterfly work dominates."""
    from ..utils import config as _config

    if _config.env_flag("DG16_FORCE_LIMB_NTT"):
        return True
    from .limb_kernels import use_pallas

    return use_pallas() and n >= 2048


@functools.partial(jax.jit, static_argnums=(1, 2))
def _limb_ntt_route(x, n: int, inverse: bool):
    """(..., n, 16) row-major <-> limb-major shim around ntt_limb (no 1/n
    scaling — the caller's ifft applies size_inv, as with _ntt_core).

    The limb pipeline works in the redundant [0, 2p) Montgomery class;
    the row-major world requires CANONICAL limbs (returning redundant
    representatives silently corrupted downstream F.mul results — caught
    by the prove_single integration test), so canon() at the boundary."""
    from .ntt_limb import lfr, ntt_limb

    batch = x.shape[:-2]
    flat = x.reshape((-1, n, N_LIMBS))

    def one(v):  # (n, 16) -> (n, 16)
        return jnp.transpose(lfr().canon(ntt_limb(jnp.transpose(v), n,
                                                  inverse)))

    out = jax.vmap(one)(flat)
    return out.reshape(batch + (n, N_LIMBS))


def _zpad(x, n):
    k = x.shape[-2]
    assert k <= n, f"input length {k} exceeds domain size {n}"
    if k == n:
        return x
    pad = [(0, 0)] * (x.ndim - 2) + [(0, n - k), (0, 0)]
    return jnp.pad(x, pad)


def _bitrev_traced(n: int):
    """(n,) int32 bit-reversal permutation as traced device ops (the numpy
    table would bake a 4·n-byte literal into any enclosing jit)."""
    return _bitrev(n, jnp)


def _powers(base: int, n: int) -> list[int]:
    out, acc = [], 1
    for _ in range(n):
        out.append(acc)
        acc = acc * base % R
    return out


def _powers_device(base: int, n: int) -> jnp.ndarray:
    """(n, 16) table of base^0..base^{n-1}, built with O(log n) device muls.

    Host work is O(1) (encode the base once); the table doubles on device:
    [b^0..b^{k-1}] -> [b^0..b^{2k-1}] via one batched multiply by b^k.
    """
    F = fr()
    logn = max(1, (n - 1).bit_length())
    # base^(2^b) for each bit, via repeated squaring on a single element —
    # all muls here share the (1, 16) shape so only one executable compiles.
    bit_pows = [F.encode([base % R])]
    for _ in range(logn - 1):
        bit_pows.append(F.mul(bit_pows[-1], bit_pows[-1]))
    # tbl[k] = prod_{b: bit b of k set} base^(2^b); logn muls of shape (n, 16).
    k = jnp.arange(n, dtype=jnp.uint32)
    tbl = jnp.broadcast_to(jnp.asarray(F.one), (n, N_LIMBS))
    for b in range(logn):
        hit = ((k >> b) & 1) == 1
        tbl = jnp.where(hit[:, None], F.mul(tbl, bit_pows[b]), tbl)
    return tbl


@functools.cache
def domain(size: int, offset: int = 1) -> JaxDomain:
    return JaxDomain(size, offset)
