#!/usr/bin/env bash
# Million-constraint workload over real sockets, 8 OS processes — the
# reference's scripts/million.zsh (groth16/examples/million.rs launcher,
# fixtures/million/million.circom = 2^20 constraints). Runs the full
# distributed prover on the chain circuit at LOG2 constraints via the
# nonlocal runner; rank 0 pairing-verifies.
#   ./scripts/million.sh              # LOG2=10 smoke
#   LOG2=20 ./scripts/million.sh     # the reference's configuration
cd "$(dirname "$0")/.."
export CIRCUIT=chain LOG2=${LOG2:-10}
exec bash scripts/nonlocal_sha256.sh
