#!/bin/bash
# Phase-2 (circuit-specific) proving-key ceremony — analog of the
# reference's scripts/phase2_proving_key.sh (snarkjs groth16 setup over a
# powers-of-tau file, contribute, beacon, verify, export).
#
# snarkjs is an EXTERNAL npm toolchain this image does not ship. The
# framework covers the same capability surface two ways:
#
#   * dev-grade circuit-specific setup natively on device:
#     models/groth16/setup.py (seeded, like the reference service's
#     [42u8;32] dev setup — mpc-api/src/main.rs:148-152). No ptau file.
#   * REAL-ceremony keys: frontend/zkey.py reads (and writes) snarkjs
#     .zkey files, so a circuit_final.zkey produced by this exact
#     ceremony elsewhere drops in via ProvingKey.from_zkey(...).
#
# If snarkjs + a ptau file are available this script runs the same
# ceremony the reference's does; otherwise it prints the recipe.
set -euo pipefail
cd "$(dirname "$0")/.."

R1CS=${1:-}
PTAU=${2:-powersOfTau28_hez_final_22.ptau}
OUTDIR=${3:-artifacts}
if [ -z "$R1CS" ]; then
  echo "usage: scripts/phase2_proving_key.sh circuit.r1cs [ptau] [outdir]"
  exit 2
fi

if ! command -v npx >/dev/null 2>&1 || [ ! -f "$PTAU" ]; then
  cat <<EOF
snarkjs (npx) or the ptau file is unavailable here.

Run the ceremony on a machine with node + snarkjs
(https://github.com/iden3/snarkjs):

    npx snarkjs groth16 setup $R1CS $PTAU $OUTDIR/circuit_0000.zkey
    echo "test" | npx snarkjs zkey contribute $OUTDIR/circuit_0000.zkey \\
        $OUTDIR/circuit_0001.zkey --name="1st Contributor" -v
    npx snarkjs zkey beacon $OUTDIR/circuit_0001.zkey \\
        $OUTDIR/circuit_final.zkey \\
        0102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f 10
    npx snarkjs zkey verify $R1CS $PTAU $OUTDIR/circuit_final.zkey
    npx snarkjs zkey export verificationkey $OUTDIR/circuit_final.zkey \\
        $OUTDIR/verification_key.json

then load it here with ProvingKey.from_zkey("$OUTDIR/circuit_final.zkey").
For development, models/groth16/setup.py produces a working (dev-trust)
key with no external toolchain at all.
EOF
  exit 3
fi

mkdir -p "$OUTDIR"
npx snarkjs groth16 setup "$R1CS" "$PTAU" "$OUTDIR/circuit_0000.zkey"
echo "test" | npx snarkjs zkey contribute "$OUTDIR/circuit_0000.zkey" \
  "$OUTDIR/circuit_0001.zkey" --name="1st Contributor" -v
npx snarkjs zkey beacon "$OUTDIR/circuit_0001.zkey" \
  "$OUTDIR/circuit_final.zkey" \
  0102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f 10 \
  -n="Final Beacon phase2"
npx snarkjs zkey verify "$R1CS" "$PTAU" "$OUTDIR/circuit_final.zkey"
npx snarkjs zkey export verificationkey "$OUTDIR/circuit_final.zkey" \
  "$OUTDIR/verification_key.json"
echo "Done"
