"""CRS in-the-exponent packing micro-bench (the million-workload CPU
bottleneck: 74-84% of wall-clock rode the row-major ladders; on TPU the
same packexp ladders ride the limb-major Pallas kernels — VERDICT r3 #6).

Times pp.packexp_from_public over BN254 G1 at --log2-m points (the S-query
shape: m points packed l at a time into n-share groups), reporting
points/sec and the jit-compile split. Compare against the per-proof MSM
time at the same m: the done-bar is packing <= prove.

Usage: python scripts/profile_packing.py [--log2-m 15] [--n 8] [--l 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2-m", type=int, default=15)
    ap.add_argument("--n", type=int, default=8)
    ap.add_argument("--l", type=int, default=2)
    args = ap.parse_args()

    import jax

    from distributed_groth16_tpu.utils.cache import setup_compile_cache

    setup_compile_cache(jax, os.path.join(os.path.dirname(__file__), ".."))
    import jax.numpy as jnp
    import numpy as np

    from distributed_groth16_tpu.ops.constants import G1_GENERATOR
    from distributed_groth16_tpu.ops.curve import g1
    from distributed_groth16_tpu.parallel.pss import PackedSharingParams

    plat = jax.devices()[0].platform
    m = 1 << args.log2_m
    assert args.n == 4 * args.l, "PSS requires n = 4l"
    pp = PackedSharingParams(args.l)
    C1 = g1()

    # m points arranged (m/l, l) for pack-consecutive semantics
    base = C1.encode([G1_GENERATOR])[0]
    pts = jnp.broadcast_to(base, (m // args.l, args.l, 3, 16))

    t0 = time.time()
    out = pp.packexp_from_public(C1, pts)
    np.asarray(out)  # host sync = compile + first run
    cold = time.time() - t0

    t0 = time.time()
    out = pp.packexp_from_public(C1, pts)
    np.asarray(out)
    warm = time.time() - t0

    # scalar route (r5): what the same m costs when the dealer knows the
    # discrete logs — field-NTT pack + windowed fixed-base
    # (models/groth16/proving_key.py _pack_query_scalars)
    from distributed_groth16_tpu.models.groth16.proving_key import (
        _pack_query_scalars,
    )
    from distributed_groth16_tpu.ops.field import fr

    scal = fr().encode(list(range(2, m + 2)))
    t0 = time.time()
    outs = _pack_query_scalars("g1", pp, scal)
    np.asarray(outs)
    scalar_cold = time.time() - t0
    t0 = time.time()
    outs = _pack_query_scalars("g1", pp, scal)
    np.asarray(outs)
    scalar_warm = time.time() - t0

    print(
        json.dumps(
            {
                "metric": "crs_packexp_points_per_sec",
                "platform": plat,
                "log2_m": args.log2_m,
                "n": args.n,
                "l": args.l,
                "warm_s": round(warm, 2),
                "cold_s": round(cold, 2),
                "points_per_sec": round(m / warm, 1),
                "scalar_route_warm_s": round(scalar_warm, 2),
                "scalar_route_cold_s": round(scalar_cold, 2),
                "scalar_route_points_per_sec": round(m / scalar_warm, 1),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
