#!/usr/bin/env bash
# Distributed partial products over real sockets, 8 OS processes — the
# reference's scripts/dpp_test.zsh (dist-primitives/examples/dpp_test.rs
# launcher).
#   ./scripts/dpp_test.sh             # m=128 smoke
#   M=2048 ./scripts/dpp_test.sh     # bigger vector
cd "$(dirname "$0")/.."
EXAMPLE=examples/nonlocal_kernel.py
EXTRA_ARGS=(--kernel dpp --m "${M:-128}")
source scripts/_launch_ranks.sh
echo "dpp_test: OK"
