#!/bin/bash
# Tunnel watchdog: probe the axon TPU backend on a loop; the moment a
# probe succeeds, fire scripts/tpu_session.sh (the one-shot measurement
# program) and exit. Probes run in a subprocess with a hard timeout
# because a half-open tunnel HANGS make_c_api_client rather than failing
# (observed round 5: >120 s wedge under JAX_PLATFORMS=cpu even).
#
# Usage: scripts/tpu_watch.sh [logdir] [probe_timeout_s] [interval_s]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch}
PROBE_T=${2:-420}
INTERVAL=${3:-480}
mkdir -p "$LOG"
stamp() { date -u +%H:%M:%S; }
note() { echo "$(stamp) $*" | tee -a "$LOG/watch.log"; }

note "=== tpu_watch start (probe_timeout=${PROBE_T}s interval=${INTERVAL}s)"
i=0
while true; do
  i=$((i + 1))
  t0=$(date +%s)
  out=$(timeout "$PROBE_T" python -c \
    "import jax; d=jax.devices()[0]; print(d.platform)" 2>&1 | tail -1; \
    exit "${PIPESTATUS[0]}")
  rc=$?
  dt=$(( $(date +%s) - t0 ))
  note "probe #$i rc=$rc dt=${dt}s out=${out}"
  if [ "$rc" -eq 0 ] && { [ "$out" = "tpu" ] || [ "$out" = "axon" ]; }; then
    note "tunnel UP — firing tpu_session.sh"
    bash scripts/tpu_session.sh "$LOG/session"
    note "session complete; exiting watchdog"
    exit 0
  fi
  sleep "$INTERVAL"
done
