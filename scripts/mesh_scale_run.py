"""Mesh-prover scale run: the FULL SPMD proving step at a real domain size
(default m=4096, n=8 parties) on an 8-device mesh, checked against the
host-oracle proof core. Records the evidence for VERDICT r2 weak #3/#4 —
the mesh path executing beyond toy shapes.

Run (CPU, 8 virtual devices — same mode as the driver's dryrun):
    python scripts/mesh_scale_run.py [--m 4096] [--check]
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _ROOT)

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags).strip()
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from distributed_groth16_tpu.utils.cache import setup_compile_cache

setup_compile_cache(jax, _ROOT)

import jax.numpy as jnp
import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--m", type=int, default=4096)
    p.add_argument("--check", action="store_true",
                   help="verify the proof cores against the host oracle")
    args = p.parse_args()

    from distributed_groth16_tpu.frontend.r1cs import mult_chain_circuit
    from distributed_groth16_tpu.models.groth16 import (
        CompiledR1CS,
        pack_proving_key,
        setup,
        verify,
    )
    from distributed_groth16_tpu.models.groth16.mesh_prover import (
        MeshProverInputs,
        mesh_prove,
    )
    from distributed_groth16_tpu.models.groth16.prove import (
        pack_from_witness,
        prove_single,
    )
    from distributed_groth16_tpu.ops.field import fr
    from distributed_groth16_tpu.parallel.mesh import make_mesh
    from distributed_groth16_tpu.parallel.pss import PackedSharingParams
    from distributed_groth16_tpu.utils.timers import PhaseTimings, phase

    timings = PhaseTimings()
    l = 2
    pp = PackedSharingParams(l)
    nc = args.m - 2
    with phase("build circuit", timings):
        cs = mult_chain_circuit(999992, nc)
        r1cs, z = cs.finish()
    with phase("setup", timings):
        pk = setup(r1cs)
    m = pk.domain_size
    assert m >= args.m, (m, args.m)
    F = fr()
    z_mont = F.encode(z)
    comp = CompiledR1CS(r1cs)

    with phase("packing", timings):
        qap_shares = comp.qap(z_mont).pss(pp)
        crs = pack_proving_key(pk, pp)
        a_sh = pack_from_witness(pp, z_mont[1:])
        ax_sh = pack_from_witness(pp, z_mont[r1cs.num_instance:])

        def stack(get):
            return jnp.stack([get(i) for i in range(pp.n)])

        inp = MeshProverInputs(
            qap_a=stack(lambda i: qap_shares[i].a),
            qap_b=stack(lambda i: qap_shares[i].b),
            qap_c=stack(lambda i: qap_shares[i].c),
            a_share=a_sh,
            ax_share=ax_sh,
            s=stack(lambda i: crs[i].s),
            u=stack(lambda i: crs[i].u),
            v=stack(lambda i: crs[i].v),
            w=stack(lambda i: crs[i].w),
        )

    mesh = make_mesh(pp.n)
    with phase("mesh prove (compile+run)", timings):
        t0 = time.time()
        pa, pb, pc = mesh_prove(pp, m, mesh, inp)
        jax.block_until_ready((pa, pb, pc))
        total = time.time() - t0
    with phase("mesh prove (steady-state rerun)", timings):
        pa, pb, pc = mesh_prove(pp, m, mesh, inp)
        jax.block_until_ready((pa, pb, pc))

    print(f"mesh proving step ran at m={m}, n={pp.n} parties "
          f"(first call incl. compile: {total:.1f}s)")
    if args.check:
        with phase("host-oracle check", timings):
            single = prove_single(pk, comp, z_mont)
            from distributed_groth16_tpu.models.groth16.prove import (
                PartyProofShare,
                reassemble_proof,
            )
            share = PartyProofShare(a=pa, b=pb, c=pc)
            proof = reassemble_proof(share, pk)
            ok = verify(pk.vk, proof, z[1:r1cs.num_instance])
            match = (proof.a, proof.b, proof.c) == (
                single.a, single.b, single.c,
            )
            print(f"pairing verify: {ok}; matches single-node: {match}")
            if not (ok and match):
                return 1
    print("phase timings (ms):")
    for k, v in timings.as_millis().items():
        print(f"  {k:34s} {v:12.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
