#!/usr/bin/env bash
# Distributed MSM over real sockets, 8 OS processes — the reference's
# scripts/dmsm_bench.zsh (dist-primitives/examples/dmsm_bench.rs launcher).
#   ./scripts/dmsm_bench.sh           # m=64 smoke
#   M=1024 ./scripts/dmsm_bench.sh   # bigger MSM
cd "$(dirname "$0")/.."
EXAMPLE=examples/nonlocal_kernel.py
EXTRA_ARGS=(--kernel dmsm --m "${M:-64}")
source scripts/_launch_ranks.sh
echo "dmsm_bench: OK"
