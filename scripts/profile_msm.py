"""Piece-wise timing of the tree MSM on the real chip: which stage owns the
per-MSM milliseconds (sort+gather / up-sweep / Fenwick+combine / Horner)?

Run on an idle machine (single TPU process):  python scripts/profile_msm.py
Prints one line per variant using the same marginal-cost methodology as
bench.py (jitted K-loop, host-sync fence).
"""

from __future__ import annotations

import os
import sys


sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

from distributed_groth16_tpu.utils.cache import setup_compile_cache

setup_compile_cache(
    jax, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

import jax.numpy as jnp
import numpy as np

from distributed_groth16_tpu.ops.constants import G1_GENERATOR, R
from distributed_groth16_tpu.ops.curve import g1
from distributed_groth16_tpu.ops import limb_kernels as lk
from distributed_groth16_tpu.ops.msm import encode_scalars_std

LOG2N = int(os.environ.get("PROF_LOG2N", "16"))
N = 1 << LOG2N
C = 8


from distributed_groth16_tpu.utils.benchtools import marginal_cost


def marginal(make_fn, *args):
    return marginal_cost(make_fn, args, reps=3)


def main():
    rng = np.random.default_rng(0)
    scalars = encode_scalars_std(
        [int.from_bytes(rng.bytes(40), "little") % R for _ in range(N)]
    )
    points = jnp.broadcast_to(g1().encode([G1_GENERATOR])[0], (N, 3, 16))
    g = lk.lg1()
    W = 256 // C

    def var_full(k):
        @jax.jit
        def run(points, scalars):
            acc = jnp.uint32(0)
            for i in range(k):
                acc += lk._msm_tree_jit.__wrapped__(
                    g, points, scalars ^ jnp.uint32(i), C, None
                ).sum(dtype=jnp.uint32)
            return acc

        return run

    def var_sort_gather(k):
        @jax.jit
        def run(points, scalars):
            lm = g.from_rowmajor(points)
            acc = jnp.uint32(0)
            for i in range(k):
                digits = lk._digits(scalars ^ jnp.uint32(i), C)  # (W, n)
                order = jnp.argsort(digits, axis=-1)
                gathered = jnp.take(lm, order.reshape(-1), axis=1)
                acc += gathered.sum(dtype=jnp.uint32)
            return acc

        return run

    def var_sort_only(k):
        @jax.jit
        def run(points, scalars):
            acc = jnp.uint32(0)
            for i in range(k):
                digits = lk._digits(scalars ^ jnp.uint32(i), C)
                order = jnp.argsort(digits, axis=-1)
                acc += order.sum(dtype=jnp.int32).astype(jnp.uint32)
            return acc

        return run

    def var_upsweep(k):
        # up-sweep only: tree adds over (48, W, n) without Fenwick/combine
        @jax.jit
        def run(points, scalars):
            lm = g.from_rowmajor(points)
            acc = jnp.uint32(0)
            for i in range(k):
                digits = lk._digits(scalars ^ jnp.uint32(i), C)
                order = jnp.argsort(digits, axis=-1)
                gathered = jnp.take(lm, order.reshape(-1), axis=1).reshape(
                    48, W, N
                )
                x = gathered
                while x.shape[-1] > 1:
                    half = x.shape[-1] // 2
                    pair = x.reshape(48, W, half, 2)
                    x = g.add(pair[..., 0], pair[..., 1])
                acc += x.sum(dtype=jnp.uint32)
            return acc

        return run

    full = marginal(var_full, points, scalars)
    sort_only = marginal(var_sort_only, points, scalars)
    sort_gather = marginal(var_sort_gather, points, scalars)
    upsweep = marginal(var_upsweep, points, scalars)
    print(f"n=2^{LOG2N} c={C}  (per-MSM marginal seconds)")
    print(f"full tree msm      : {full*1e3:9.1f} ms  ({N/full:,.0f} muls/s)")
    print(f"sort only          : {sort_only*1e3:9.1f} ms")
    print(f"sort+gather        : {sort_gather*1e3:9.1f} ms")
    print(f"sort+gather+upsweep: {upsweep*1e3:9.1f} ms")
    print(f"=> fenwick+combine+horner ≈ {(full-upsweep)*1e3:9.1f} ms")


if __name__ == "__main__":
    main()
