#!/usr/bin/env python
"""Subprocess-per-module test runner: the safe way to run the full suite
with the persistent compile cache ON.

A single-process pytest run must disable the jax persistent compilation
cache (XLA:CPU AOT loader segfaults, tests/conftest.py) and therefore
cold-compiles every kernel — hours on this box. This runner instead
launches ONE pytest process PER test module with the cache enabled
(DG16_TEST_CACHE=1): a cache-poisoning crash takes down one module's
process, is detected by its signal exit, and that module is retried once
with the cache disabled. Modules share warm compilations through the
on-disk cache, so the suite converges to compile-once.

Usage: python scripts/run_tests.py [pytest args, e.g. -m "not slow"]
Exit 0 iff every module passed (rc 0 or 5 = nothing collected).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRASH_RCS = {132, 134, 136, 137, 139}  # SIGILL/ABRT/FPE/KILL/SEGV via shell


def run_module(path: str, extra: list[str], cache: bool) -> tuple[int, float]:
    env = dict(os.environ)
    env["DG16_TEST_CACHE"] = "1"
    if cache:
        env.pop("DG16_NO_JAX_CACHE", None)
    else:
        env["DG16_NO_JAX_CACHE"] = "1"
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, "-m", "pytest", path, "-q", *extra],
        cwd=ROOT,
        env=env,
    )
    return r.returncode, time.time() - t0


def main() -> int:
    extra = sys.argv[1:]
    modules = sorted(glob.glob(os.path.join(ROOT, "tests", "test_*.py")))
    if not modules:
        print("no test modules found")
        return 1
    failed: list[str] = []
    t_suite = time.time()
    for path in modules:
        name = os.path.basename(path)
        rc, dt = run_module(path, extra, cache=True)
        crashed = rc < 0 or rc in CRASH_RCS
        if crashed:
            print(
                f"== {name}: crashed (rc={rc}) with cache on — "
                "retrying cache-off",
                flush=True,
            )
            rc, dt = run_module(path, extra, cache=False)
        status = "ok" if rc in (0, 5) else f"FAILED rc={rc}"
        print(f"== {name}: {status} ({dt:.1f}s)", flush=True)
        if rc not in (0, 5):
            failed.append(name)
    total = time.time() - t_suite
    print(
        f"== suite: {len(modules) - len(failed)}/{len(modules)} modules "
        f"passed in {total:.0f}s"
    )
    if failed:
        print("== failed modules: " + ", ".join(failed))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
