#!/usr/bin/env bash
# Full distributed Groth16 prover over real mTLS sockets: generate per-rank
# certs, launch an 8-process star, wait for every rank, propagate failures.
# The reference's scripts/sha256.zsh role for nonlocal_sha256.rs:126.
#
#   ./scripts/nonlocal_sha256.sh                # chain circuit, fast smoke
#   CIRCUIT=sha256 ./scripts/nonlocal_sha256.sh # the full sha256 workload
#   PLAIN=1 ...                                 # plain TCP, no TLS
set -euo pipefail
cd "$(dirname "$0")/.."

N=${N:-8}
PORT=${PORT:-9785}
CIRCUIT=${CIRCUIT:-chain}
LOG2=${LOG2:-10}
WORK=${WORK_DIR:-$(mktemp -d)}
if [ -z "${WORK_DIR:-}" ]; then trap 'rm -rf "$WORK"' EXIT; fi

EXTRA=()
if [ "${PLAIN:-0}" = "1" ]; then
  EXTRA+=(--plain)
else
  for i in $(seq 0 $((N - 1))); do
    python -m distributed_groth16_tpu.utils.certs "$i" "$WORK/certs" >/dev/null
  done
fi

ADDR="$WORK/addresses"
for i in $(seq 0 $((N - 1))); do
  echo "127.0.0.1:$((PORT + i))" >> "$ADDR"
done

# the axon TPU plugin can hang backend init when PALLAS_AXON_POOL_IPS is
# set; ranks run on the CPU backend
unset PALLAS_AXON_POOL_IPS
PIDS=()
for i in $(seq $((N - 1)) -1 0); do
  JAX_PLATFORMS=${NL_PLATFORM:-cpu} python examples/nonlocal_sha256.py \
    --id "$i" --input "$ADDR" --certs "$WORK/certs" --n "$N" \
    --circuit "$CIRCUIT" --log2-constraints "$LOG2" "${EXTRA[@]}" \
    > "$WORK/rank$i.log" 2>&1 &
  PIDS+=($!)
done

STATUS=0
for pid in "${PIDS[@]}"; do
  wait "$pid" || STATUS=1
done
grep -h "pairing verification" "$WORK"/rank*.log || true
if [ "$STATUS" -ne 0 ]; then
  echo "nonlocal_sha256: FAILED — logs:"
  tail -n 20 "$WORK"/rank*.log
  echo "nonlocal_sha256: FAILED"
else
  echo "nonlocal_sha256: OK"
fi
exit $STATUS
