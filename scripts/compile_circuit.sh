#!/bin/bash
# Circuit regeneration — analog of the reference's scripts/compile_circuit.sh
# (circom -> .r1cs/.wasm/.sym for fixtures/*.circom).
#
# The circom compiler is an EXTERNAL toolchain (Rust binary / npm package)
# that this image does not ship, and the framework deliberately does not
# reimplement it: the framework's ingestion boundary is the COMPILED
# artifact pair (.r1cs + .wasm), which frontend/readers.py and
# frontend/wasm_vm.py consume natively. If circom is on PATH this script
# performs the same compilation the reference's does; otherwise it
# documents the exact command so the artifacts can be produced on any
# machine with circom and copied in.
#
# Everything DOWNSTREAM of the artifacts is covered natively:
#   .r1cs/.wasm parsing      frontend/readers.py, frontend/wasm_vm.py
#   witness generation       frontend/witness_calculator.py (+ csrc C tier)
#   setup / proving          models/groth16 (no ptau needed: dev setup)
#   snarkjs interop          frontend/snarkjs.py, frontend/zkey.py
set -euo pipefail
cd "$(dirname "$0")/.."

CIRCUIT=${1:-}
OUTDIR=${2:-artifacts}
if [ -z "$CIRCUIT" ]; then
  echo "usage: scripts/compile_circuit.sh path/to/circuit.circom [outdir]"
  exit 2
fi

if ! command -v circom >/dev/null 2>&1; then
  cat <<EOF
circom not found on PATH.

This environment does not ship the circom compiler; compile the circuit
on a machine that has it (https://docs.circom.io):

    circom --r1cs --wasm --sym -o $OUTDIR $CIRCUIT

then copy the resulting .r1cs and _js/*.wasm pair here. The framework
consumes them directly:

    from distributed_groth16_tpu.frontend.builder import CircomConfig
    cfg = CircomConfig("$OUTDIR/<name>_js/<name>.wasm", "$OUTDIR/<name>.r1cs")
EOF
  exit 3
fi

echo "Compiling $CIRCUIT"
mkdir -p "$OUTDIR"
circom --r1cs --wasm --sym -o "$OUTDIR" "$CIRCUIT"
echo "Done"
