"""Staged TPU probe: incremental JSON lines, smallest compiles first.

Diagnoses where the remote-TPU time goes before committing to the full
bench.py program: (0) trivial dispatch, (1) the Pallas G1 add kernel at a
few batch widths, (2) the fused NTT kernel, (3) a small tree MSM, then
(4) the headline sizes. Each stage prints its own line immediately, so a
wedged tunnel or a pathological compile is visible mid-run rather than as
45 minutes of silence.

Usage: python scripts/tpu_probe.py [--stages 0,1,2,3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def emit(**kw):
    print(json.dumps(kw), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="0,1,2,3")
    ap.add_argument("--msm-log2n", type=int, default=12)
    args = ap.parse_args()
    stages = {int(s) for s in args.stages.split(",")}

    t0 = time.time()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_groth16_tpu.utils.cache import setup_compile_cache

    setup_compile_cache(jax, os.path.join(os.path.dirname(__file__), ".."))

    plat = jax.devices()[0].platform
    emit(stage="init", platform=plat, t=round(time.time() - t0, 1))

    from distributed_groth16_tpu.utils.benchtools import marginal_cost

    if 0 in stages:
        t = time.time()
        x = jnp.arange(8192, dtype=jnp.uint32)
        y = int((x * x + jnp.uint32(3)).sum())
        emit(stage="trivial", ok=y > 0, t=round(time.time() - t, 1))

    if 1 in stages:
        from distributed_groth16_tpu.ops.limb_kernels import lg1

        g = lg1()
        for log2n in (14, 17, 20):
            n = 1 << log2n
            t = time.time()
            # random-ish valid points: broadcast generator, vary via double
            from distributed_groth16_tpu.ops.constants import G1_GENERATOR
            from distributed_groth16_tpu.ops.curve import g1

            base = g1().encode([G1_GENERATOR])[0]
            pts = jnp.broadcast_to(base.reshape(48, 1), (48, n))

            def make(k: int):
                @jax.jit
                def run(p):
                    acc = p
                    for _ in range(k):
                        acc = g._pallas_add(acc, p) if plat == "tpu" else g._xla_add(acc, p)
                    return acc[0].sum(dtype=jnp.uint32)

                return run

            per = marginal_cost(make, (pts,))
            emit(
                stage="pallas_add",
                log2n=log2n,
                adds_per_sec=round(n / per),
                per_call_ms=round(per * 1e3, 2),
                compile_s=round(time.time() - t, 1),
            )

    if 2 in stages:
        from distributed_groth16_tpu.ops.ntt_limb import ntt_limb

        rng = np.random.default_rng(1)
        for log2n in (12, 16, 20):
            n = 1 << log2n
            t = time.time()
            x = jnp.asarray(
                rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32)
            )

            def make(k: int):
                @jax.jit
                def run(x):
                    acc = jnp.uint32(0)
                    for i in range(k):
                        out = ntt_limb(x ^ jnp.uint32(i), n, False)
                        acc = acc + out.sum(dtype=jnp.uint32)
                    return acc

                return run

            per = marginal_cost(make, (x,))
            emit(
                stage="ntt",
                log2n=log2n,
                per_call_ms=round(per * 1e3, 2),
                compile_s=round(time.time() - t, 1),
            )

    if 3 in stages:
        from distributed_groth16_tpu.ops.constants import G1_GENERATOR, R
        from distributed_groth16_tpu.ops.curve import g1
        from distributed_groth16_tpu.ops.limb_kernels import _msm_tree_jit, lg1
        from distributed_groth16_tpu.ops.msm import encode_scalars_std

        inner = _msm_tree_jit.__wrapped__
        rng = np.random.default_rng(2)
        n = 1 << args.msm_log2n
        t = time.time()
        scalars = encode_scalars_std(
            [int.from_bytes(rng.bytes(40), "little") % R for _ in range(n)]
        )
        points = jnp.broadcast_to(g1().encode([G1_GENERATOR])[0], (n, 3, 16))

        def make(k: int):
            @jax.jit
            def run(points, scalars):
                acc = jnp.uint32(0)
                for i in range(k):
                    sc = scalars ^ jnp.uint32(i)
                    out = inner(lg1(), points, sc, 8, None)
                    acc = acc + out.sum(dtype=jnp.uint32)
                return acc

            return run

        per = marginal_cost(make, (points, scalars))
        emit(
            stage="msm_tree",
            log2n=args.msm_log2n,
            muls_per_sec=round(n / per),
            per_msm_ms=round(per * 1e3, 1),
            compile_s=round(time.time() - t, 1),
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
