"""Staged TPU probe: incremental JSON lines, smallest compiles first.

Diagnoses where the remote-TPU time goes before committing to the full
bench.py program: (0) trivial dispatch, (1) the Pallas G1 add kernel at a
few batch widths, (4) bit-exact MSM correctness vs the host oracle, (2)
the fused NTT kernel, (3) a small tree MSM. Stages run IN THE ORDER GIVEN
on the command line — the default puts the correctness gate before the
big-compile throughput stages. Each stage prints its own line immediately,
so a wedged tunnel or a pathological compile is visible mid-run rather
than as 45 minutes of silence.

Usage: python scripts/tpu_probe.py [--stages 0,1,4,2,3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def emit(**kw):
    print(json.dumps(kw), flush=True)


def _stage_trivial(jax, jnp, np, plat, args):
    t = time.time()
    x = jnp.arange(8192, dtype=jnp.uint32)
    y = int((x * x + jnp.uint32(3)).sum())
    emit(stage="trivial", ok=y > 0, t=round(time.time() - t, 1))


def _stage_add_kernel(jax, jnp, np, plat, args):
    from distributed_groth16_tpu.ops.constants import G1_GENERATOR
    from distributed_groth16_tpu.ops.curve import g1
    from distributed_groth16_tpu.ops.limb_kernels import lg1
    from distributed_groth16_tpu.utils.benchtools import marginal_cost

    g = lg1()
    for log2n in (14, 17, 20):
        n = 1 << log2n
        t = time.time()
        base = g1().encode([G1_GENERATOR])[0]
        pts = jnp.broadcast_to(base.reshape(48, 1), (48, n))
        add1 = g._pallas_add if plat == "tpu" else g._xla_add

        @jax.jit
        def run(p, k):
            def body(i, acc):
                return add1(acc, p)

            return jax.lax.fori_loop(0, k, body, p)[0].sum(dtype=jnp.uint32)

        def make(k: int, _run=run):
            return lambda p: _run(p, k)

        per = marginal_cost(make, (pts,))
        emit(
            stage="pallas_add",
            log2n=log2n,
            adds_per_sec=round(n / per),
            per_call_ms=round(per * 1e3, 2),
            compile_s=round(time.time() - t, 1),
        )


def _stage_ntt(jax, jnp, np, plat, args):
    from distributed_groth16_tpu.ops.ntt_limb import ntt_limb
    from distributed_groth16_tpu.utils.benchtools import marginal_cost

    rng = np.random.default_rng(1)
    for log2n in (12, 16, 20):
        n = 1 << log2n
        t = time.time()
        x = jnp.asarray(rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32))

        @jax.jit
        def run(x, k):
            def body(i, acc):
                out = ntt_limb(x ^ i.astype(jnp.uint32), n, False)
                return acc + out.sum(dtype=jnp.uint32)

            return jax.lax.fori_loop(0, k, body, jnp.uint32(0))

        def make(k: int, _run=run):
            return lambda x: _run(x, k)

        per = marginal_cost(make, (x,))
        emit(
            stage="ntt",
            log2n=log2n,
            per_call_ms=round(per * 1e3, 2),
            compile_s=round(time.time() - t, 1),
        )


def _stage_msm_correctness(jax, jnp, np, plat, args):
    # correctness on the REAL chip: the Pallas fast path has only ever
    # executed under XLA:CPU (use_pallas gates it off-TPU); Mosaic's
    # lowering of the u32 limb arithmetic must be validated bit-exactly
    # before any throughput number means anything.
    from distributed_groth16_tpu.ops import refmath as rm
    from distributed_groth16_tpu.ops.constants import G1_GENERATOR, R
    from distributed_groth16_tpu.ops.curve import g1
    from distributed_groth16_tpu.ops.limb_kernels import msm_tree
    from distributed_groth16_tpu.ops.msm import encode_scalars_std

    rng = np.random.default_rng(3)
    n = 512
    t = time.time()
    scal = [int.from_bytes(rng.bytes(40), "little") % R for _ in range(n)]
    pts = [rm.G1.scalar_mul(G1_GENERATOR, i + 1) for i in range(n)]
    out = msm_tree(g1().encode(pts), encode_scalars_std(scal))
    got = g1().decode(np.asarray(out)[None])[0]
    want = rm.G1.msm(pts, scal)
    emit(
        stage="msm_correctness",
        n=n,
        ok=bool(got == want),
        t=round(time.time() - t, 1),
    )
    if got != want:
        emit(stage="msm_correctness_detail", got=str(got), want=str(want))
        raise SystemExit(1)


def _stage_msm_perf(jax, jnp, np, plat, args):
    from distributed_groth16_tpu.ops.constants import G1_GENERATOR, R
    from distributed_groth16_tpu.ops.curve import g1
    from distributed_groth16_tpu.ops.limb_kernels import _msm_tree_jit, lg1
    from distributed_groth16_tpu.ops.msm import encode_scalars_std
    from distributed_groth16_tpu.utils.benchtools import marginal_cost

    inner = _msm_tree_jit.__wrapped__
    rng = np.random.default_rng(2)
    n = 1 << args.msm_log2n
    t = time.time()
    scalars = encode_scalars_std(
        [int.from_bytes(rng.bytes(40), "little") % R for _ in range(n)]
    )
    points = jnp.broadcast_to(g1().encode([G1_GENERATOR])[0], (n, 3, 16))

    @jax.jit
    def run(points, scalars, k):
        def body(i, acc):
            sc = scalars ^ i.astype(jnp.uint32)
            out = inner(lg1(), points, sc, 8, None)
            return acc + out.sum(dtype=jnp.uint32)

        return jax.lax.fori_loop(0, k, body, jnp.uint32(0))

    def make(k: int):
        return lambda points, scalars: run(points, scalars, k)

    per = marginal_cost(make, (points, scalars))
    emit(
        stage="msm_tree",
        log2n=args.msm_log2n,
        muls_per_sec=round(n / per),
        per_msm_ms=round(per * 1e3, 1),
        compile_s=round(time.time() - t, 1),
    )


_STAGES = {
    0: _stage_trivial,
    1: _stage_add_kernel,
    2: _stage_ntt,
    3: _stage_msm_perf,
    4: _stage_msm_correctness,
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="0,1,4,2,3")
    ap.add_argument("--msm-log2n", type=int, default=12)
    args = ap.parse_args()
    order = [int(s) for s in args.stages.split(",")]
    unknown = [s for s in order if s not in _STAGES]
    if unknown:
        emit(stage="warn", unknown_stages=unknown, known=sorted(_STAGES))
        order = [s for s in order if s in _STAGES]

    t0 = time.time()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_groth16_tpu.utils.cache import setup_compile_cache

    setup_compile_cache(jax, os.path.join(os.path.dirname(__file__), ".."))

    plat = jax.devices()[0].platform
    emit(stage="init", platform=plat, t=round(time.time() - t0, 1))

    for s in order:
        _STAGES[s](jax, jnp, np, plat, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
