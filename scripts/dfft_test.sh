#!/usr/bin/env bash
# Distributed FFT over real sockets, 8 OS processes — the reference's
# scripts/dfft_test.zsh (dist-primitives/examples/dfft_test.rs launcher).
#   ./scripts/dfft_test.sh            # m=256 smoke
#   M=4096 ./scripts/dfft_test.sh    # bigger transform
cd "$(dirname "$0")/.."
EXAMPLE=examples/nonlocal_kernel.py
EXTRA_ARGS=(--kernel dfft --m "${M:-256}")
source scripts/_launch_ranks.sh
echo "dfft_test: OK"
