#!/bin/bash
# One-shot TPU measurement session: run the moment the axon tunnel answers.
# 1. bench.py (tree-MSM 2^16 + 2^20 lanes + NTT 2^20) -> JSON line
# 2. single-node sha256 prove wall-clock on the chip (BASELINE config 1)
# Usage: bash scripts/tpu_session.sh [logfile]
set -u
LOG=${1:-/tmp/tpu_session.log}
cd "$(dirname "$0")/.."
echo "=== bench.py ($(date -u +%FT%TZ)) ===" | tee -a "$LOG"
timeout 3600 python bench.py 2>&1 | tee -a "$LOG"
echo "=== sha256 e2e single-node on chip ===" | tee -a "$LOG"
timeout 7200 python examples/sha256.py --skip-mpc 2>&1 | tail -20 | tee -a "$LOG"
echo "=== done ($(date -u +%FT%TZ)) ===" | tee -a "$LOG"
