#!/bin/bash
# One-shot TPU measurement session: runs the full on-chip program in value
# order, each stage logged and time-bounded, continuing past failures.
# Designed to be fired automatically the moment the tunnel recovers (the
# window may be short): small compiles first, so a wedge costs the least.
#
# Usage: scripts/tpu_session.sh [logdir]   (default /tmp/tpu_session)
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_session}
mkdir -p "$LOG"
stamp() { date -u +%H:%M:%S; }
note() { echo "$(stamp) $*" | tee -a "$LOG/session.log"; }

note "=== TPU session start"

# A: tunnel sanity + add-kernel throughput + bit-exact MSM correctness +
#    2^12 MSM perf (same program bench stage 1 will reuse from the cache)
note "stage A: probe 0,1,4,3 @2^12"
timeout 2700 python scripts/tpu_probe.py --stages 0,1,4,3 --msm-log2n 12 \
  > "$LOG/probe.json" 2> "$LOG/probe.log"
note "stage A exit=$? ($(tail -c 200 "$LOG/probe.json" 2>/dev/null | tr -d '\n'))"

# B: the round bench — staged 12/16/20 sweep + NTT, watchdog-protected
note "stage B: bench.py"
DG16_BENCH_BUDGET_S=2700 timeout 3300 python bench.py \
  > "$LOG/bench.json" 2> "$LOG/bench.log"
b_exit=$?
note "stage B exit=$b_exit ($(tail -c 300 "$LOG/bench.json" 2>/dev/null | tr -d '\n'))"

# C: packing micro-bench at 2^15 (VERDICT #6 done-bar: packing <= prove)
note "stage C: profile_packing @2^15"
timeout 2700 python scripts/profile_packing.py --log2-m 15 \
  > "$LOG/packing.json" 2> "$LOG/packing.log"
note "stage C exit=$? ($(tail -c 200 "$LOG/packing.json" 2>/dev/null | tr -d '\n'))"

# D: end-to-end sha256 single-node prove on the chip (BASELINE config 1)
note "stage D: sha256 e2e --skip-mpc"
timeout 5400 python examples/sha256.py --skip-mpc \
  > "$LOG/sha256.log" 2>&1
note "stage D exit=$? ($(tail -c 300 "$LOG/sha256.log" 2>/dev/null | tr -d '\n'))"

# E: only if the fori bench completed — measure the unrolled-body steady
# state too (removes the fori loop overhead at a much higher compile
# cost); whichever is faster becomes the round-5 default.
if [ "$b_exit" -eq 0 ] && grep -q '"platform": "tpu"' "$LOG/bench.json" 2>/dev/null; then
  note "stage E: bench.py DG16_PALLAS_ROLL=unroll"
  DG16_PALLAS_ROLL=unroll DG16_BENCH_BUDGET_S=2400 timeout 3000 python bench.py \
    > "$LOG/bench_unroll.json" 2> "$LOG/bench_unroll.log"
  note "stage E exit=$? ($(tail -c 300 "$LOG/bench_unroll.json" 2>/dev/null | tr -d '\n'))"
fi

note "=== TPU session done"
