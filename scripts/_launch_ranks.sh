# Shared rank-spawning harness for the per-kernel launcher matrix
# (dfft_test.sh, dmsm_bench.sh, dpp_test.sh, million.sh) — the role the
# reference's scripts/{dfft_test,dmsm_bench,dpp_test,million}.zsh share:
# generate certs + address file, spawn N ranks of a given example,
# wait for all, propagate any failure. Sourced, not executed.
#
# Caller sets: EXAMPLE (python file), EXTRA_ARGS (array, per-rank args
# appended after --id/--input/--certs/--n). Honors N, PORT, PLAIN,
# WORK_DIR, NL_PLATFORM like nonlocal_sha256.sh, plus ROUND_RETRIES
# (default 1): a failed round — any rank exiting non-zero, e.g. on a
# transient MpcNetError — relaunches ALL ranks up to that many extra
# times before the harness reports failure.

set -euo pipefail

N=${N:-8}
PORT=${PORT:-9805}
WORK=${WORK_DIR:-$(mktemp -d)}
if [ -z "${WORK_DIR:-}" ]; then trap 'rm -rf "$WORK"' EXIT; fi

TLS_ARGS=()
if [ "${PLAIN:-0}" = "1" ]; then
  TLS_ARGS+=(--plain)
else
  for i in $(seq 0 $((N - 1))); do
    python -m distributed_groth16_tpu.utils.certs "$i" "$WORK/certs" >/dev/null
  done
fi

ADDR="$WORK/addresses"
: > "$ADDR"
for i in $(seq 0 $((N - 1))); do
  echo "127.0.0.1:$((PORT + i))" >> "$ADDR"
done

# the axon TPU plugin can hang backend init when PALLAS_AXON_POOL_IPS is
# set; ranks run on the CPU backend unless NL_PLATFORM overrides
unset PALLAS_AXON_POOL_IPS

ROUND_RETRIES=${ROUND_RETRIES:-1}
ATTEMPT=0
while :; do
  PIDS=()
  for i in $(seq $((N - 1)) -1 0); do
    JAX_PLATFORMS=${NL_PLATFORM:-cpu} python "$EXAMPLE" \
      --id "$i" --input "$ADDR" --certs "$WORK/certs" --n "$N" \
      "${EXTRA_ARGS[@]}" "${TLS_ARGS[@]}" \
      > "$WORK/rank$i.log" 2>&1 &
    PIDS+=($!)
  done

  STATUS=0
  for pid in "${PIDS[@]}"; do
    wait "$pid" || STATUS=1
  done
  if [ "$STATUS" -eq 0 ] || [ "$ATTEMPT" -ge "$ROUND_RETRIES" ]; then
    break
  fi
  ATTEMPT=$((ATTEMPT + 1))
  echo "$(basename "$EXAMPLE"): round failed; retry $ATTEMPT/$ROUND_RETRIES"
done

grep -h "rank 0:" "$WORK"/rank*.log || true
if [ "$STATUS" -ne 0 ]; then
  echo "$(basename "$EXAMPLE"): FAILED after $((ATTEMPT + 1)) attempt(s) — logs:"
  tail -n 20 "$WORK"/rank*.log
  exit 1
fi
