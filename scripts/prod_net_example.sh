#!/usr/bin/env bash
# Prod-net integration smoke: generate per-rank certs, launch a 5-process
# mTLS star, check the sum-of-ids result — the reference's
# scripts/prod_net_example.sh role (.github/workflows/ci.yml:85-96).
set -euo pipefail
cd "$(dirname "$0")/.."

N=5
PORT=${PORT:-9745}
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

for i in $(seq 0 $((N - 1))); do
  python -m distributed_groth16_tpu.utils.certs "$i" "$WORK/certs" >/dev/null
done

ADDR="$WORK/addresses"
for i in $(seq 0 $((N - 1))); do
  echo "127.0.0.1:$((PORT + i))" >> "$ADDR"
done

PIDS=()
for i in $(seq $((N - 1)) -1 0); do
  python examples/add_ids.py --id "$i" --input "$ADDR" --certs "$WORK/certs" --n $N &
  PIDS+=($!)
done

STATUS=0
for pid in "${PIDS[@]}"; do
  wait "$pid" || STATUS=1
done
if [ "$STATUS" -eq 0 ]; then
  echo "prod_net_example: OK"
else
  echo "prod_net_example: FAILED"
fi
exit $STATUS
