/* Native execution tier for the circom WASM witness generator.
 *
 * Executes the SAME pre-decoded flat instruction stream as the pure-Python
 * interpreter (distributed_groth16_tpu/frontend/wasm_vm.py — decoded form:
 * one [op, a, b, c] quad per instruction, control structure pre-resolved
 * into end/else pcs), so the two engines are differentially testable
 * instruction-for-instruction. Plays the role wasmer plays for the
 * reference (ark-circom/src/witness/witness_calculator.rs:56-153): the
 * pure-Python VM needs ~7 minutes for the sha256 fixture witness; this
 * tier runs the identical semantics at C speed.
 *
 * Scope: the integer-only WASM MVP subset circom emits (i32/i64 arith +
 * comparisons, all integer load/store widths, block/loop/if/br/br_if/
 * br_table, call/call_indirect, globals, linear memory). Traps and host
 * calls (runtime.*) surface through a callback + trap-code protocol; the
 * Python side re-raises its own exceptions.
 *
 * Value representation matches the Python VM: every stack slot is an
 * unsigned 64-bit integer; i32 results are masked to 32 bits.
 */

#include <setjmp.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define PAGE 65536
#define VALUE_STACK_CAP (1 << 20)
#define FRAME_POOL_CAP (1 << 20) /* shared heap pool, not per-call C stack */
#define CALL_DEPTH_CAP 8192

/* trap codes (mirrored in wasm_cexec.py) */
enum {
    WX_OK = 0,
    WX_TRAP_UNREACHABLE = 1,
    WX_TRAP_DIV_ZERO = 2,
    WX_TRAP_OVERFLOW = 3,
    WX_TRAP_BAD_TABLE = 4,
    WX_TRAP_BAD_OP = 5,
    WX_TRAP_STACK = 6,
    WX_TRAP_HOST = 7,  /* host callback raised; Python holds the exception */
    WX_TRAP_OOM = 8,
    WX_TRAP_OOB = 9, /* out-of-bounds linear-memory access */
};

#if defined(__GNUC__) && !defined(WX_NO_THREADING)
#define WX_THREADED 1
#else
#define WX_THREADED 0
#endif

#if WX_THREADED
/* labels-as-values dispatch: NEXT() loads the next instruction and jumps
 * straight to its handler (bounds-checked: a hostile pre-decoded stream
 * could carry an op outside the byte range). */
#define OP(x) L_##x:
#define OP_DEFAULT L_BAD:
#define NEXT()                                                               \
    do {                                                                     \
        if (pc >= ncode) goto func_return;                                   \
        I = &code[pc];                                                       \
        pc++;                                                                \
        goto *((uint64_t)I->op < 256 ? optable[I->op] : &&L_BAD);            \
    } while (0)
#else
#define OP(x) case x:
#define OP_DEFAULT default:
#define NEXT() break
#endif

typedef struct {
    int64_t op, a, b, c;
} Ins;

/* host callback: returns the (single) result value; sets *trap nonzero to
 * abort execution (the Python side stores the pending exception). */
typedef uint64_t (*HostFn)(int32_t import_idx, const uint64_t *args,
                           int32_t nargs, int32_t *trap);

typedef struct {
    const Ins *ins;          /* all local function bodies, concatenated */
    const int64_t *func_off; /* nfuncs+1 offsets into ins */
    const int64_t *func_locals;
    const int64_t *func_nparams;
    const int64_t *func_nresults;
    const int64_t *type_nparams;  /* per type index (call_indirect) */
    const int64_t *type_nresults;
    const int64_t *imp_nparams; /* per import index */
    const int64_t *imp_nresults;
    const int64_t *br_pool; /* flattened br_table targets */
    int64_t *table;         /* funcref table (global func indices; -1 empty) */
    int64_t ntable;
    int64_t *globals;
    uint8_t *memory;
    int64_t *cur_pages; /* in/out */
    int64_t max_pages;
    int64_t n_imports;
    int64_t nfuncs;
    HostFn host;

    uint64_t *vstack; /* shared value stack (points GUARD slots into
                       * vstack_alloc: hostile-module stack underflow
                       * stays inside our allocation — see wx_new) */
    uint64_t *vstack_alloc;
    int64_t guard;
    jmp_buf trap_jmp;
    int32_t trap_code;
    int64_t call_depth;
    /* control-frame pool shared across the call chain: a per-call
     * stack-allocated array was 128KB of C stack per recursion level,
     * exhausting the thread stack (SIGSEGV) long before CALL_DEPTH_CAP
     * could trap */
    void *frames; /* Frame[FRAME_POOL_CAP] */
    int64_t frame_base;
} Engine;

static void trap(Engine *E, int code) {
    E->trap_code = code;
    longjmp(E->trap_jmp, 1);
}

static inline int64_t s32(uint64_t v) { return (int64_t)(int32_t)(uint32_t)v; }
static inline int64_t s64(uint64_t v) { return (int64_t)v; }
#define M32 0xFFFFFFFFu

/* execute local function `lf` (0-based local index). args (nparams) are in
 * vstack starting at `base`; on return, results (nresults) land at `base`.
 */
static void exec_func(Engine *E, int64_t lf, int64_t base);

/* call by GLOBAL function index with nargs values on the vstack top;
 * consumes them and pushes results. `sp` is the value-stack top pointer
 * index (points one past the last arg). Returns the new sp. */
static int64_t do_call(Engine *E, int64_t fi, int64_t sp) {
    if (fi < E->n_imports) {
        int64_t np = E->imp_nparams[fi], nr = E->imp_nresults[fi];
        int32_t t = 0;
        uint64_t r = E->host((int32_t)fi, E->vstack + sp - np, (int32_t)np, &t);
        if (t) trap(E, WX_TRAP_HOST);
        sp -= np;
        if (nr) E->vstack[sp++] = r & M32; /* VM masks host results to u32 */
        return sp;
    }
    int64_t lf = fi - E->n_imports;
    int64_t np = E->func_nparams[lf], nr = E->func_nresults[lf];
    int64_t base = sp - np;
    if (++E->call_depth > CALL_DEPTH_CAP) trap(E, WX_TRAP_STACK);
    exec_func(E, lf, base);
    E->call_depth--;
    return base + nr;
}

typedef struct {
    uint8_t is_loop;
    int64_t target;  /* pc to jump to on branch */
    int64_t height;  /* value-stack height (relative sp) to unwind to */
    int64_t arity;
} Frame;

/* bounds-checked memory access: the engine executes UNTRUSTED modules
 * (the API server runs client-uploaded witness generators), so every
 * load/store validates addr+width against the CURRENT memory size —
 * overflow-safely: `a_ + width` can wrap at 2^64 for a hostile address,
 * so compare against size - width instead. */
#define MEMADDR(E, addr, width)                                              \
    ({                                                                       \
        uint64_t a_ = (addr);                                                \
        uint64_t msz_ = (uint64_t)(*(E)->cur_pages) * PAGE;                  \
        if (msz_ < (width) || a_ > msz_ - (width))                           \
            trap((E), WX_TRAP_OOB);                                          \
        (E)->memory + a_;                                                    \
    })

static void exec_func(Engine *E, int64_t lf, int64_t base) {
    const Ins *code = E->ins + E->func_off[lf];
    const int64_t ncode = E->func_off[lf + 1] - E->func_off[lf];
    const int64_t nloc = E->func_nparams[lf] + E->func_locals[lf];
    const int64_t nres = E->func_nresults[lf];
    /* capacity check BEFORE touching the locals region, with headroom for
     * the WHOLE body: net stack growth is bounded by the instruction
     * count (each instruction pushes at most one value), so an untrusted
     * body can never run sp past the cap between checks */
    if (base + nloc + ncode + 8 > VALUE_STACK_CAP) trap(E, WX_TRAP_STACK);
    uint64_t *loc = E->vstack + base;
    /* zero the non-param locals; value stack begins after the locals */
    memset(loc + E->func_nparams[lf], 0,
           (size_t)E->func_locals[lf] * sizeof(uint64_t));
    int64_t sp = base + nloc; /* absolute index into vstack */
    uint64_t *st = E->vstack;
    const int64_t fb = E->frame_base;
    Frame *frames = (Frame *)E->frames + fb;
    int64_t nf = 0;
    int64_t pc = 0;

    const Ins *I;
#if WX_THREADED
    /* token-threaded dispatch (GCC labels-as-values):each opcode body ends
     * with its own indirect jump, so the branch predictor learns
     * per-predecessor opcode patterns — the interpreter-dispatch win the
     * reference gets from wasmer's JIT compilation
     * (ark-circom/src/witness/witness_calculator.rs:56-153) approximated
     * without emitting native code. The switch build below remains the
     * portable fallback (-DWX_NO_THREADING or non-GCC). */
    static const void *optable[256] = {
        [0 ... 255] = &&L_BAD,
        [0x20] = &&L_0x20, [0x41] = &&L_0x41, [0x42] = &&L_0x42, [0x21] = &&L_0x21, [0x22] = &&L_0x22, [0x28] = &&L_0x28, [0x36] = &&L_0x36, [0x29] = &&L_0x29, [0x37] = &&L_0x37, [0x6A] = &&L_0x6A, [0x7C] = &&L_0x7C, [0x02] = &&L_0x02, [0x03] = &&L_0x03, [0x04] = &&L_0x04, [0x05] = &&L_0x05, [0x0B] = &&L_0x0B, [0x0C] = &&L_0x0C, [0x0D] = &&L_0x0D, [0x0E] = &&L_0x0E, [0x0F] = &&L_0x0F, [0x10] = &&L_0x10, [0x11] = &&L_0x11, [0x1A] = &&L_0x1A, [0x1B] = &&L_0x1B, [0x23] = &&L_0x23, [0x24] = &&L_0x24, [0x2C] = &&L_0x2C, [0x2D] = &&L_0x2D, [0x2E] = &&L_0x2E, [0x2F] = &&L_0x2F, [0x30] = &&L_0x30, [0x31] = &&L_0x31, [0x32] = &&L_0x32, [0x33] = &&L_0x33, [0x34] = &&L_0x34, [0x35] = &&L_0x35, [0x3A] = &&L_0x3A, [0x3B] = &&L_0x3B, [0x3C] = &&L_0x3C, [0x3D] = &&L_0x3D, [0x3E] = &&L_0x3E, [0x3F] = &&L_0x3F, [0x40] = &&L_0x40, [0x45] = &&L_0x45, [0x46] = &&L_0x46, [0x47] = &&L_0x47, [0x48] = &&L_0x48, [0x49] = &&L_0x49, [0x4A] = &&L_0x4A, [0x4B] = &&L_0x4B, [0x4C] = &&L_0x4C, [0x4D] = &&L_0x4D, [0x4E] = &&L_0x4E, [0x4F] = &&L_0x4F, [0x50] = &&L_0x50, [0x51] = &&L_0x51, [0x52] = &&L_0x52, [0x53] = &&L_0x53, [0x54] = &&L_0x54, [0x55] = &&L_0x55, [0x56] = &&L_0x56, [0x57] = &&L_0x57, [0x58] = &&L_0x58, [0x59] = &&L_0x59, [0x5A] = &&L_0x5A, [0x67] = &&L_0x67, [0x68] = &&L_0x68, [0x69] = &&L_0x69, [0x6B] = &&L_0x6B, [0x6C] = &&L_0x6C, [0x6D] = &&L_0x6D, [0x6E] = &&L_0x6E, [0x6F] = &&L_0x6F, [0x70] = &&L_0x70, [0x71] = &&L_0x71, [0x72] = &&L_0x72, [0x73] = &&L_0x73, [0x74] = &&L_0x74, [0x75] = &&L_0x75, [0x76] = &&L_0x76, [0x77] = &&L_0x77, [0x78] = &&L_0x78, [0x79] = &&L_0x79, [0x7A] = &&L_0x7A, [0x7B] = &&L_0x7B, [0x7D] = &&L_0x7D, [0x7E] = &&L_0x7E, [0x7F] = &&L_0x7F, [0x80] = &&L_0x80, [0x81] = &&L_0x81, [0x82] = &&L_0x82, [0x83] = &&L_0x83, [0x84] = &&L_0x84, [0x85] = &&L_0x85, [0x86] = &&L_0x86, [0x87] = &&L_0x87, [0x88] = &&L_0x88, [0xA7] = &&L_0xA7, [0xAC] = &&L_0xAC, [0xAD] = &&L_0xAD, [0x00] = &&L_0x00, [0x01] = &&L_0x01
    };
    NEXT();
#else
    while (pc < ncode) {
        I = &code[pc];
        pc++;
        switch (I->op) {
#endif
        OP(0x20) st[sp++] = loc[I->a]; NEXT();            /* local.get */
        OP(0x41) OP(0x42) st[sp++] = (uint64_t)I->a; NEXT(); /* const */
        OP(0x21) loc[I->a] = st[--sp]; NEXT();            /* local.set */
        OP(0x22) loc[I->a] = st[sp - 1]; NEXT();          /* local.tee */
        OP(0x28) { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = v; NEXT(); }                /* i32.load */
        OP(0x36) { uint64_t v = st[--sp]; uint32_t w = (uint32_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 4), &w, 4); NEXT(); }
        OP(0x29) { uint64_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 8), 8);
                     st[sp-1] = v; NEXT(); }                /* i64.load */
        OP(0x37) { uint64_t v = st[--sp];
                     memcpy(MEMADDR(E, st[--sp] + I->a, 8), &v, 8); NEXT(); }
        OP(0x6A) { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] + v) & M32; NEXT(); } /* i32.add */
        OP(0x7C) { uint64_t v = st[--sp];
                     st[sp-1] = st[sp-1] + v; NEXT(); }     /* i64.add */
        OP(0x02) /* block */
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){0, I->b + 1, sp, I->a};
            NEXT();
        OP(0x03) /* loop */
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){1, pc, sp, 0};
            NEXT();
        OP(0x04) { /* if: a=arity, b=end_pc, c=else_pc */
            uint64_t cond = st[--sp];
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){0, I->b + 1, sp, I->a};
            if (!cond) pc = (I->c != -1) ? I->c : I->b;
            NEXT(); }
        OP(0x05) pc = I->b; NEXT(); /* else marker: jump to end instr */
        OP(0x0B) /* end */
            if (I->a == -1) goto func_return;
            nf--;
            NEXT();
        OP(0x0C) OP(0x0D) OP(0x0E) { /* br / br_if / br_table */
            int64_t depth;
            if (I->op == 0x0D) {
                if (!st[--sp]) NEXT();
                depth = I->a;
            } else if (I->op == 0x0E) {
                uint64_t k = st[--sp];
                depth = (k < (uint64_t)I->b) ? E->br_pool[I->a + k] : I->c;
            } else {
                depth = I->a;
            }
            if (depth >= nf) { nf = 0; goto func_return; }
            nf -= depth;
            Frame *F = &frames[nf - 1];
            if (F->is_loop) { sp = F->height; pc = F->target; NEXT(); }
            {   int64_t ar = F->arity;
                if (ar) memmove(st + F->height, st + sp - ar,
                                (size_t)ar * sizeof(uint64_t));
                sp = F->height + ar;
                nf--;
                pc = F->target;
            }
            NEXT(); }
        OP(0x0F) goto func_return; /* return */
        OP(0x10) /* call */
            E->frame_base = fb + nf;
            sp = do_call(E, I->a, sp);
            E->frame_base = fb;
            NEXT();
        OP(0x11) { /* call_indirect: a = type idx */
            uint64_t k = st[--sp];
            if (k >= (uint64_t)E->ntable || E->table[k] < 0)
                trap(E, WX_TRAP_BAD_TABLE);
            E->frame_base = fb + nf;
            sp = do_call(E, E->table[k], sp);
            E->frame_base = fb;
            NEXT(); }
        OP(0x1A) sp--; NEXT(); /* drop */
        OP(0x1B) { uint64_t c = st[--sp], b2 = st[--sp];
                     if (!c) { st[sp-1] = b2; }
                     NEXT(); } /* select */
        OP(0x23) st[sp++] = (uint64_t)E->globals[I->a]; NEXT();
        OP(0x24) E->globals[I->a] = (int64_t)st[--sp]; NEXT();
        OP(0x2C) { uint8_t v = *MEMADDR(E, st[sp-1] + I->a, 1);
                     st[sp-1] = (uint64_t)((int8_t)v) & M32; NEXT(); }
        OP(0x2D) st[sp-1] = *MEMADDR(E, st[sp-1] + I->a, 1); NEXT();
        OP(0x2E) { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = (uint64_t)((int16_t)v) & M32; NEXT(); }
        OP(0x2F) { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = v; NEXT(); }
        OP(0x30) { uint8_t v = *MEMADDR(E, st[sp-1] + I->a, 1);
                     st[sp-1] = (uint64_t)(int64_t)(int8_t)v; NEXT(); }
        OP(0x31) st[sp-1] = *MEMADDR(E, st[sp-1] + I->a, 1); NEXT();
        OP(0x32) { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = (uint64_t)(int64_t)(int16_t)v; NEXT(); }
        OP(0x33) { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = v; NEXT(); }
        OP(0x34) { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = (uint64_t)(int64_t)(int32_t)v; NEXT(); }
        OP(0x35) { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = v; NEXT(); }
        OP(0x3A) { uint64_t v = st[--sp];
                     *MEMADDR(E, st[--sp] + I->a, 1) = (uint8_t)v; NEXT(); }
        OP(0x3B) { uint64_t v = st[--sp]; uint16_t w = (uint16_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 2), &w, 2); NEXT(); }
        OP(0x3C) { uint64_t v = st[--sp];
                     *MEMADDR(E, st[--sp] + I->a, 1) = (uint8_t)v; NEXT(); }
        OP(0x3D) { uint64_t v = st[--sp]; uint16_t w = (uint16_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 2), &w, 2); NEXT(); }
        OP(0x3E) { uint64_t v = st[--sp]; uint32_t w = (uint32_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 4), &w, 4); NEXT(); }
        OP(0x3F) st[sp++] = (uint64_t)*E->cur_pages; NEXT();
        OP(0x40) { /* memory.grow (buffer pre-sized to max_pages) */
            uint64_t delta = st[--sp];
            int64_t old = *E->cur_pages;
            if (old + (int64_t)delta > E->max_pages) trap(E, WX_TRAP_OOM);
            *E->cur_pages = old + (int64_t)delta;
            st[sp++] = (uint64_t)old;
            NEXT(); }
        OP(0x45) st[sp-1] = (st[sp-1] == 0); NEXT(); /* i32.eqz */
        OP(0x46) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] == v); NEXT(); }
        OP(0x47) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] != v); NEXT(); }
        OP(0x48) { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) < v); NEXT(); }
        OP(0x49) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] < v); NEXT(); }
        OP(0x4A) { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) > v); NEXT(); }
        OP(0x4B) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] > v); NEXT(); }
        OP(0x4C) { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) <= v); NEXT(); }
        OP(0x4D) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] <= v); NEXT(); }
        OP(0x4E) { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) >= v); NEXT(); }
        OP(0x4F) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] >= v); NEXT(); }
        OP(0x50) st[sp-1] = (st[sp-1] == 0); NEXT(); /* i64.eqz */
        OP(0x51) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] == v); NEXT(); }
        OP(0x52) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] != v); NEXT(); }
        OP(0x53) { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) < v); NEXT(); }
        OP(0x54) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] < v); NEXT(); }
        OP(0x55) { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) > v); NEXT(); }
        OP(0x56) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] > v); NEXT(); }
        OP(0x57) { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) <= v); NEXT(); }
        OP(0x58) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] <= v); NEXT(); }
        OP(0x59) { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) >= v); NEXT(); }
        OP(0x5A) { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] >= v); NEXT(); }
        OP(0x67) { uint32_t v = (uint32_t)st[sp-1];
                     st[sp-1] = v ? (uint64_t)__builtin_clz(v) : 32; NEXT(); }
        OP(0x68) { uint32_t v = (uint32_t)st[sp-1];
                     st[sp-1] = v ? (uint64_t)__builtin_ctz(v) : 32; NEXT(); }
        OP(0x69) st[sp-1] = (uint64_t)__builtin_popcountll(st[sp-1] & M32);
                   NEXT();
        OP(0x6B) { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] - v) & M32; NEXT(); }
        OP(0x6C) { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] * v) & M32; NEXT(); }
        OP(0x6D) { int64_t v = s32(st[--sp]); int64_t a = s32(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     if (a == INT32_MIN && v == -1) trap(E, WX_TRAP_OVERFLOW);
                     st[sp-1] = (uint64_t)(a / v) & M32; NEXT(); }
        OP(0x6E) { uint64_t v = st[--sp] & M32;
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (st[sp-1] & M32) / v; NEXT(); }
        OP(0x6F) { int64_t v = s32(st[--sp]); int64_t a = s32(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (uint64_t)(a % v) & M32; NEXT(); }
        OP(0x70) { uint64_t v = st[--sp] & M32;
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (st[sp-1] & M32) % v; NEXT(); }
        OP(0x71) { uint64_t v = st[--sp]; st[sp-1] &= v; NEXT(); }
        OP(0x72) { uint64_t v = st[--sp]; st[sp-1] |= v; NEXT(); }
        OP(0x73) { uint64_t v = st[--sp]; st[sp-1] ^= v; NEXT(); }
        OP(0x74) { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (st[sp-1] << v) & M32; NEXT(); }
        OP(0x75) { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (uint64_t)(s32(st[sp-1]) >> v) & M32; NEXT(); }
        OP(0x76) { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (st[sp-1] & M32) >> v; NEXT(); }
        OP(0x77) { uint64_t v = st[--sp] & 31; uint32_t a = (uint32_t)st[sp-1];
                     st[sp-1] = v ? ((a << v) | (a >> (32 - v))) : a; NEXT(); }
        OP(0x78) { uint64_t v = st[--sp] & 31; uint32_t a = (uint32_t)st[sp-1];
                     st[sp-1] = v ? ((a >> v) | (a << (32 - v))) : a; NEXT(); }
        OP(0x79) st[sp-1] = st[sp-1] ? (uint64_t)__builtin_clzll(st[sp-1])
                                       : 64; NEXT();
        OP(0x7A) st[sp-1] = st[sp-1] ? (uint64_t)__builtin_ctzll(st[sp-1])
                                       : 64; NEXT();
        OP(0x7B) st[sp-1] = (uint64_t)__builtin_popcountll(st[sp-1]); NEXT();
        OP(0x7D) { uint64_t v = st[--sp]; st[sp-1] -= v; NEXT(); }
        OP(0x7E) { uint64_t v = st[--sp]; st[sp-1] *= v; NEXT(); }
        OP(0x7F) { int64_t v = s64(st[--sp]); int64_t a = s64(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     if (a == INT64_MIN && v == -1) trap(E, WX_TRAP_OVERFLOW);
                     st[sp-1] = (uint64_t)(a / v); NEXT(); }
        OP(0x80) { uint64_t v = st[--sp];
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] /= v; NEXT(); }
        OP(0x81) { int64_t v = s64(st[--sp]); int64_t a = s64(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     /* INT64_MIN % -1 is UB in C (SIGFPE); wasm says 0 */
                     st[sp-1] = (a == INT64_MIN && v == -1)
                                    ? 0 : (uint64_t)(a % v);
                     NEXT(); }
        OP(0x82) { uint64_t v = st[--sp];
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] %= v; NEXT(); }
        OP(0x83) { uint64_t v = st[--sp]; st[sp-1] &= v; NEXT(); }
        OP(0x84) { uint64_t v = st[--sp]; st[sp-1] |= v; NEXT(); }
        OP(0x85) { uint64_t v = st[--sp]; st[sp-1] ^= v; NEXT(); }
        OP(0x86) { uint64_t v = st[--sp] & 63; st[sp-1] <<= v; NEXT(); }
        OP(0x87) { uint64_t v = st[--sp] & 63;
                     st[sp-1] = (uint64_t)(s64(st[sp-1]) >> v); NEXT(); }
        OP(0x88) { uint64_t v = st[--sp] & 63; st[sp-1] >>= v; NEXT(); }
        OP(0xA7) st[sp-1] &= M32; NEXT();        /* i32.wrap_i64 */
        OP(0xAC) st[sp-1] = (uint64_t)(int64_t)s32(st[sp-1]); NEXT();
        OP(0xAD) NEXT();                         /* i64.extend_i32_u */
        OP(0x00) trap(E, WX_TRAP_UNREACHABLE);
        OP(0x01) NEXT();                         /* nop */
        OP_DEFAULT trap(E, WX_TRAP_BAD_OP);
#if !WX_THREADED
        }
    }
#endif
func_return:
    /* move the top nres values down to base (results of the function) */
    if (nres)
        memmove(E->vstack + base, E->vstack + sp - nres,
                (size_t)nres * sizeof(uint64_t));
}

/* ---- public API ---------------------------------------------------------- */

Engine *wx_new(const int64_t *ins_flat, int64_t n_ins,
               const int64_t *func_off, int64_t nfuncs,
               const int64_t *func_locals, const int64_t *func_nparams,
               const int64_t *func_nresults, const int64_t *type_nparams,
               const int64_t *type_nresults, const int64_t *imp_nparams,
               const int64_t *imp_nresults, int64_t n_imports,
               const int64_t *br_pool, int64_t /*n_pool*/ n_pool,
               int64_t *table, int64_t ntable, int64_t *globals,
               uint8_t *memory, int64_t *cur_pages, int64_t max_pages,
               HostFn host) {
    (void)n_pool;
    Engine *E = (Engine *)calloc(1, sizeof(Engine));
    if (!E) return NULL;
    /* keep our own copies of the immutable arrays (the Python side frees
     * its temporaries after wx_new) */
    size_t insz = (size_t)n_ins * sizeof(Ins);
    Ins *ins = (Ins *)malloc(insz ? insz : 1);
    memcpy(ins, ins_flat, insz);
#define COPY(name, n)                                                        \
    do {                                                                     \
        size_t sz = (size_t)(n) * sizeof(int64_t);                           \
        int64_t *p = (int64_t *)malloc(sz ? sz : 1);                         \
        memcpy(p, (name), sz);                                               \
        E->name = p;                                                         \
    } while (0)
    E->ins = ins;
    COPY(func_off, nfuncs + 1);
    COPY(func_locals, nfuncs);
    COPY(func_nparams, nfuncs);
    COPY(func_nresults, nfuncs);
    COPY(type_nparams, 1024); /* generous fixed copy; Python pads */
    COPY(type_nresults, 1024);
    COPY(imp_nparams, n_imports ? n_imports : 1);
    COPY(imp_nresults, n_imports ? n_imports : 1);
    COPY(br_pool, n_pool ? n_pool : 1);
#undef COPY
    /* mutable state stays shared with Python */
    E->table = table;
    E->ntable = ntable;
    E->globals = globals;
    E->memory = memory;
    E->cur_pages = cur_pages;
    E->max_pages = max_pages;
    E->n_imports = n_imports;
    E->nfuncs = nfuncs;
    E->host = host;
    /* underflow guard: an unbalanced (hostile) function body can pop at
     * most 3 values per instruction below its base, and every base is
     * >= 0, so a guard band of 3*max_body_len slots below the logical
     * stack keeps ALL underflowing accesses inside this allocation
     * (garbage values, but memory-safe) */
    int64_t max_body = 0;
    for (int64_t f = 0; f < nfuncs; f++) {
        int64_t len = func_off[f + 1] - func_off[f];
        if (len > max_body) max_body = len;
    }
    E->guard = 3 * max_body + 64;
    E->vstack_alloc = (uint64_t *)calloc(
        (size_t)(E->guard + VALUE_STACK_CAP), sizeof(uint64_t));
    E->frames = malloc(FRAME_POOL_CAP * sizeof(Frame));
    if (!E->vstack_alloc || !E->frames) {
        free(E->vstack_alloc); free(E->frames); free(E); return NULL;
    }
    E->vstack = E->vstack_alloc + E->guard;
    return E;
}

void wx_free(Engine *E) {
    if (!E) return;
    free((void *)E->ins);
    free((void *)E->func_off);
    free((void *)E->func_locals);
    free((void *)E->func_nparams);
    free((void *)E->func_nresults);
    free((void *)E->type_nparams);
    free((void *)E->type_nresults);
    free((void *)E->imp_nparams);
    free((void *)E->imp_nresults);
    free((void *)E->br_pool);
    free(E->vstack_alloc);
    free(E->frames);
    free(E);
}

/* call a LOCAL function by global index; args/results via buf. Returns a
 * trap code (WX_OK on success); *nresults is set on success. */
int32_t wx_call(Engine *E, int64_t fi, const uint64_t *args, int32_t nargs,
                uint64_t *results, int32_t *nresults) {
    E->trap_code = WX_OK;
    E->call_depth = 0;
    E->frame_base = 0;
    if (setjmp(E->trap_jmp)) return E->trap_code;
    int64_t lf = fi - E->n_imports;
    if (lf < 0 || lf >= E->nfuncs) return WX_TRAP_BAD_TABLE;
    memcpy(E->vstack, args, (size_t)nargs * sizeof(uint64_t));
    exec_func(E, lf, 0);
    int64_t nr = E->func_nresults[lf];
    memcpy(results, E->vstack, (size_t)nr * sizeof(uint64_t));
    *nresults = (int32_t)nr;
    return WX_OK;
}
