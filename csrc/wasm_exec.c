/* Native execution tier for the circom WASM witness generator.
 *
 * Executes the SAME pre-decoded flat instruction stream as the pure-Python
 * interpreter (distributed_groth16_tpu/frontend/wasm_vm.py — decoded form:
 * one [op, a, b, c] quad per instruction, control structure pre-resolved
 * into end/else pcs), so the two engines are differentially testable
 * instruction-for-instruction. Plays the role wasmer plays for the
 * reference (ark-circom/src/witness/witness_calculator.rs:56-153): the
 * pure-Python VM needs ~7 minutes for the sha256 fixture witness; this
 * tier runs the identical semantics at C speed.
 *
 * Scope: the integer-only WASM MVP subset circom emits (i32/i64 arith +
 * comparisons, all integer load/store widths, block/loop/if/br/br_if/
 * br_table, call/call_indirect, globals, linear memory). Traps and host
 * calls (runtime.*) surface through a callback + trap-code protocol; the
 * Python side re-raises its own exceptions.
 *
 * Value representation matches the Python VM: every stack slot is an
 * unsigned 64-bit integer; i32 results are masked to 32 bits.
 */

#include <setjmp.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define PAGE 65536
#define VALUE_STACK_CAP (1 << 20)
#define FRAME_POOL_CAP (1 << 20) /* shared heap pool, not per-call C stack */
#define CALL_DEPTH_CAP 8192

/* trap codes (mirrored in wasm_cexec.py) */
enum {
    WX_OK = 0,
    WX_TRAP_UNREACHABLE = 1,
    WX_TRAP_DIV_ZERO = 2,
    WX_TRAP_OVERFLOW = 3,
    WX_TRAP_BAD_TABLE = 4,
    WX_TRAP_BAD_OP = 5,
    WX_TRAP_STACK = 6,
    WX_TRAP_HOST = 7,  /* host callback raised; Python holds the exception */
    WX_TRAP_OOM = 8,
    WX_TRAP_OOB = 9, /* out-of-bounds linear-memory access */
};

typedef struct {
    int64_t op, a, b, c;
} Ins;

/* host callback: returns the (single) result value; sets *trap nonzero to
 * abort execution (the Python side stores the pending exception). */
typedef uint64_t (*HostFn)(int32_t import_idx, const uint64_t *args,
                           int32_t nargs, int32_t *trap);

typedef struct {
    const Ins *ins;          /* all local function bodies, concatenated */
    const int64_t *func_off; /* nfuncs+1 offsets into ins */
    const int64_t *func_locals;
    const int64_t *func_nparams;
    const int64_t *func_nresults;
    const int64_t *type_nparams;  /* per type index (call_indirect) */
    const int64_t *type_nresults;
    const int64_t *imp_nparams; /* per import index */
    const int64_t *imp_nresults;
    const int64_t *br_pool; /* flattened br_table targets */
    int64_t *table;         /* funcref table (global func indices; -1 empty) */
    int64_t ntable;
    int64_t *globals;
    uint8_t *memory;
    int64_t *cur_pages; /* in/out */
    int64_t max_pages;
    int64_t n_imports;
    int64_t nfuncs;
    HostFn host;

    uint64_t *vstack; /* shared value stack (points GUARD slots into
                       * vstack_alloc: hostile-module stack underflow
                       * stays inside our allocation — see wx_new) */
    uint64_t *vstack_alloc;
    int64_t guard;
    jmp_buf trap_jmp;
    int32_t trap_code;
    int64_t call_depth;
    /* control-frame pool shared across the call chain: a per-call
     * stack-allocated array was 128KB of C stack per recursion level,
     * exhausting the thread stack (SIGSEGV) long before CALL_DEPTH_CAP
     * could trap */
    void *frames; /* Frame[FRAME_POOL_CAP] */
    int64_t frame_base;
} Engine;

static void trap(Engine *E, int code) {
    E->trap_code = code;
    longjmp(E->trap_jmp, 1);
}

static inline int64_t s32(uint64_t v) { return (int64_t)(int32_t)(uint32_t)v; }
static inline int64_t s64(uint64_t v) { return (int64_t)v; }
#define M32 0xFFFFFFFFu

/* execute local function `lf` (0-based local index). args (nparams) are in
 * vstack starting at `base`; on return, results (nresults) land at `base`.
 */
static void exec_func(Engine *E, int64_t lf, int64_t base);

/* call by GLOBAL function index with nargs values on the vstack top;
 * consumes them and pushes results. `sp` is the value-stack top pointer
 * index (points one past the last arg). Returns the new sp. */
static int64_t do_call(Engine *E, int64_t fi, int64_t sp) {
    if (fi < E->n_imports) {
        int64_t np = E->imp_nparams[fi], nr = E->imp_nresults[fi];
        int32_t t = 0;
        uint64_t r = E->host((int32_t)fi, E->vstack + sp - np, (int32_t)np, &t);
        if (t) trap(E, WX_TRAP_HOST);
        sp -= np;
        if (nr) E->vstack[sp++] = r & M32; /* VM masks host results to u32 */
        return sp;
    }
    int64_t lf = fi - E->n_imports;
    int64_t np = E->func_nparams[lf], nr = E->func_nresults[lf];
    int64_t base = sp - np;
    if (++E->call_depth > CALL_DEPTH_CAP) trap(E, WX_TRAP_STACK);
    exec_func(E, lf, base);
    E->call_depth--;
    return base + nr;
}

typedef struct {
    uint8_t is_loop;
    int64_t target;  /* pc to jump to on branch */
    int64_t height;  /* value-stack height (relative sp) to unwind to */
    int64_t arity;
} Frame;

/* bounds-checked memory access: the engine executes UNTRUSTED modules
 * (the API server runs client-uploaded witness generators), so every
 * load/store validates addr+width against the CURRENT memory size —
 * overflow-safely: `a_ + width` can wrap at 2^64 for a hostile address,
 * so compare against size - width instead. */
#define MEMADDR(E, addr, width)                                              \
    ({                                                                       \
        uint64_t a_ = (addr);                                                \
        uint64_t msz_ = (uint64_t)(*(E)->cur_pages) * PAGE;                  \
        if (msz_ < (width) || a_ > msz_ - (width))                           \
            trap((E), WX_TRAP_OOB);                                          \
        (E)->memory + a_;                                                    \
    })

static void exec_func(Engine *E, int64_t lf, int64_t base) {
    const Ins *code = E->ins + E->func_off[lf];
    const int64_t ncode = E->func_off[lf + 1] - E->func_off[lf];
    const int64_t nloc = E->func_nparams[lf] + E->func_locals[lf];
    const int64_t nres = E->func_nresults[lf];
    /* capacity check BEFORE touching the locals region, with headroom for
     * the WHOLE body: net stack growth is bounded by the instruction
     * count (each instruction pushes at most one value), so an untrusted
     * body can never run sp past the cap between checks */
    if (base + nloc + ncode + 8 > VALUE_STACK_CAP) trap(E, WX_TRAP_STACK);
    uint64_t *loc = E->vstack + base;
    /* zero the non-param locals; value stack begins after the locals */
    memset(loc + E->func_nparams[lf], 0,
           (size_t)E->func_locals[lf] * sizeof(uint64_t));
    int64_t sp = base + nloc; /* absolute index into vstack */
    uint64_t *st = E->vstack;
    const int64_t fb = E->frame_base;
    Frame *frames = (Frame *)E->frames + fb;
    int64_t nf = 0;
    int64_t pc = 0;

    while (pc < ncode) {
        const Ins *I = &code[pc];
        const int64_t op = I->op;
        pc++;
        switch (op) {
        case 0x20: st[sp++] = loc[I->a]; break;            /* local.get */
        case 0x41: case 0x42: st[sp++] = (uint64_t)I->a; break; /* const */
        case 0x21: loc[I->a] = st[--sp]; break;            /* local.set */
        case 0x22: loc[I->a] = st[sp - 1]; break;          /* local.tee */
        case 0x28: { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = v; break; }                /* i32.load */
        case 0x36: { uint64_t v = st[--sp]; uint32_t w = (uint32_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 4), &w, 4); break; }
        case 0x29: { uint64_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 8), 8);
                     st[sp-1] = v; break; }                /* i64.load */
        case 0x37: { uint64_t v = st[--sp];
                     memcpy(MEMADDR(E, st[--sp] + I->a, 8), &v, 8); break; }
        case 0x6A: { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] + v) & M32; break; } /* i32.add */
        case 0x7C: { uint64_t v = st[--sp];
                     st[sp-1] = st[sp-1] + v; break; }     /* i64.add */
        case 0x02: /* block */
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){0, I->b + 1, sp, I->a};
            break;
        case 0x03: /* loop */
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){1, pc, sp, 0};
            break;
        case 0x04: { /* if: a=arity, b=end_pc, c=else_pc */
            uint64_t cond = st[--sp];
            if (fb + nf >= FRAME_POOL_CAP) trap(E, WX_TRAP_STACK);
            frames[nf++] = (Frame){0, I->b + 1, sp, I->a};
            if (!cond) pc = (I->c != -1) ? I->c : I->b;
            break; }
        case 0x05: pc = I->b; break; /* else marker: jump to end instr */
        case 0x0B: /* end */
            if (I->a == -1) goto func_return;
            nf--;
            break;
        case 0x0C: case 0x0D: case 0x0E: { /* br / br_if / br_table */
            int64_t depth;
            if (op == 0x0D) {
                if (!st[--sp]) break;
                depth = I->a;
            } else if (op == 0x0E) {
                uint64_t k = st[--sp];
                depth = (k < (uint64_t)I->b) ? E->br_pool[I->a + k] : I->c;
            } else {
                depth = I->a;
            }
            if (depth >= nf) { nf = 0; goto func_return; }
            nf -= depth;
            Frame *F = &frames[nf - 1];
            if (F->is_loop) { sp = F->height; pc = F->target; break; }
            {   int64_t ar = F->arity;
                if (ar) memmove(st + F->height, st + sp - ar,
                                (size_t)ar * sizeof(uint64_t));
                sp = F->height + ar;
                nf--;
                pc = F->target;
            }
            break; }
        case 0x0F: goto func_return; /* return */
        case 0x10: /* call */
            E->frame_base = fb + nf;
            sp = do_call(E, I->a, sp);
            E->frame_base = fb;
            break;
        case 0x11: { /* call_indirect: a = type idx */
            uint64_t k = st[--sp];
            if (k >= (uint64_t)E->ntable || E->table[k] < 0)
                trap(E, WX_TRAP_BAD_TABLE);
            E->frame_base = fb + nf;
            sp = do_call(E, E->table[k], sp);
            E->frame_base = fb;
            break; }
        case 0x1A: sp--; break; /* drop */
        case 0x1B: { uint64_t c = st[--sp], b2 = st[--sp];
                     if (!c) st[sp-1] = b2; break; } /* select */
        case 0x23: st[sp++] = (uint64_t)E->globals[I->a]; break;
        case 0x24: E->globals[I->a] = (int64_t)st[--sp]; break;
        case 0x2C: { uint8_t v = *MEMADDR(E, st[sp-1] + I->a, 1);
                     st[sp-1] = (uint64_t)((int8_t)v) & M32; break; }
        case 0x2D: st[sp-1] = *MEMADDR(E, st[sp-1] + I->a, 1); break;
        case 0x2E: { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = (uint64_t)((int16_t)v) & M32; break; }
        case 0x2F: { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = v; break; }
        case 0x30: { uint8_t v = *MEMADDR(E, st[sp-1] + I->a, 1);
                     st[sp-1] = (uint64_t)(int64_t)(int8_t)v; break; }
        case 0x31: st[sp-1] = *MEMADDR(E, st[sp-1] + I->a, 1); break;
        case 0x32: { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = (uint64_t)(int64_t)(int16_t)v; break; }
        case 0x33: { uint16_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 2), 2);
                     st[sp-1] = v; break; }
        case 0x34: { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = (uint64_t)(int64_t)(int32_t)v; break; }
        case 0x35: { uint32_t v; memcpy(&v, MEMADDR(E, st[sp-1] + I->a, 4), 4);
                     st[sp-1] = v; break; }
        case 0x3A: { uint64_t v = st[--sp];
                     *MEMADDR(E, st[--sp] + I->a, 1) = (uint8_t)v; break; }
        case 0x3B: { uint64_t v = st[--sp]; uint16_t w = (uint16_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 2), &w, 2); break; }
        case 0x3C: { uint64_t v = st[--sp];
                     *MEMADDR(E, st[--sp] + I->a, 1) = (uint8_t)v; break; }
        case 0x3D: { uint64_t v = st[--sp]; uint16_t w = (uint16_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 2), &w, 2); break; }
        case 0x3E: { uint64_t v = st[--sp]; uint32_t w = (uint32_t)v;
                     memcpy(MEMADDR(E, st[--sp] + I->a, 4), &w, 4); break; }
        case 0x3F: st[sp++] = (uint64_t)*E->cur_pages; break;
        case 0x40: { /* memory.grow (buffer pre-sized to max_pages) */
            uint64_t delta = st[--sp];
            int64_t old = *E->cur_pages;
            if (old + (int64_t)delta > E->max_pages) trap(E, WX_TRAP_OOM);
            *E->cur_pages = old + (int64_t)delta;
            st[sp++] = (uint64_t)old;
            break; }
        case 0x45: st[sp-1] = (st[sp-1] == 0); break; /* i32.eqz */
        case 0x46: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] == v); break; }
        case 0x47: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] != v); break; }
        case 0x48: { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) < v); break; }
        case 0x49: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] < v); break; }
        case 0x4A: { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) > v); break; }
        case 0x4B: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] > v); break; }
        case 0x4C: { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) <= v); break; }
        case 0x4D: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] <= v); break; }
        case 0x4E: { int64_t v = s32(st[--sp]);
                     st[sp-1] = (s32(st[sp-1]) >= v); break; }
        case 0x4F: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] >= v); break; }
        case 0x50: st[sp-1] = (st[sp-1] == 0); break; /* i64.eqz */
        case 0x51: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] == v); break; }
        case 0x52: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] != v); break; }
        case 0x53: { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) < v); break; }
        case 0x54: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] < v); break; }
        case 0x55: { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) > v); break; }
        case 0x56: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] > v); break; }
        case 0x57: { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) <= v); break; }
        case 0x58: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] <= v); break; }
        case 0x59: { int64_t v = s64(st[--sp]);
                     st[sp-1] = (s64(st[sp-1]) >= v); break; }
        case 0x5A: { uint64_t v = st[--sp]; st[sp-1] = (st[sp-1] >= v); break; }
        case 0x67: { uint32_t v = (uint32_t)st[sp-1];
                     st[sp-1] = v ? (uint64_t)__builtin_clz(v) : 32; break; }
        case 0x68: { uint32_t v = (uint32_t)st[sp-1];
                     st[sp-1] = v ? (uint64_t)__builtin_ctz(v) : 32; break; }
        case 0x69: st[sp-1] = (uint64_t)__builtin_popcountll(st[sp-1] & M32);
                   break;
        case 0x6B: { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] - v) & M32; break; }
        case 0x6C: { uint64_t v = st[--sp];
                     st[sp-1] = (st[sp-1] * v) & M32; break; }
        case 0x6D: { int64_t v = s32(st[--sp]); int64_t a = s32(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     if (a == INT32_MIN && v == -1) trap(E, WX_TRAP_OVERFLOW);
                     st[sp-1] = (uint64_t)(a / v) & M32; break; }
        case 0x6E: { uint64_t v = st[--sp] & M32;
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (st[sp-1] & M32) / v; break; }
        case 0x6F: { int64_t v = s32(st[--sp]); int64_t a = s32(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (uint64_t)(a % v) & M32; break; }
        case 0x70: { uint64_t v = st[--sp] & M32;
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] = (st[sp-1] & M32) % v; break; }
        case 0x71: { uint64_t v = st[--sp]; st[sp-1] &= v; break; }
        case 0x72: { uint64_t v = st[--sp]; st[sp-1] |= v; break; }
        case 0x73: { uint64_t v = st[--sp]; st[sp-1] ^= v; break; }
        case 0x74: { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (st[sp-1] << v) & M32; break; }
        case 0x75: { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (uint64_t)(s32(st[sp-1]) >> v) & M32; break; }
        case 0x76: { uint64_t v = st[--sp] & 31;
                     st[sp-1] = (st[sp-1] & M32) >> v; break; }
        case 0x77: { uint64_t v = st[--sp] & 31; uint32_t a = (uint32_t)st[sp-1];
                     st[sp-1] = v ? ((a << v) | (a >> (32 - v))) : a; break; }
        case 0x78: { uint64_t v = st[--sp] & 31; uint32_t a = (uint32_t)st[sp-1];
                     st[sp-1] = v ? ((a >> v) | (a << (32 - v))) : a; break; }
        case 0x79: st[sp-1] = st[sp-1] ? (uint64_t)__builtin_clzll(st[sp-1])
                                       : 64; break;
        case 0x7A: st[sp-1] = st[sp-1] ? (uint64_t)__builtin_ctzll(st[sp-1])
                                       : 64; break;
        case 0x7B: st[sp-1] = (uint64_t)__builtin_popcountll(st[sp-1]); break;
        case 0x7D: { uint64_t v = st[--sp]; st[sp-1] -= v; break; }
        case 0x7E: { uint64_t v = st[--sp]; st[sp-1] *= v; break; }
        case 0x7F: { int64_t v = s64(st[--sp]); int64_t a = s64(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     if (a == INT64_MIN && v == -1) trap(E, WX_TRAP_OVERFLOW);
                     st[sp-1] = (uint64_t)(a / v); break; }
        case 0x80: { uint64_t v = st[--sp];
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] /= v; break; }
        case 0x81: { int64_t v = s64(st[--sp]); int64_t a = s64(st[sp-1]);
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     /* INT64_MIN % -1 is UB in C (SIGFPE); wasm says 0 */
                     st[sp-1] = (a == INT64_MIN && v == -1)
                                    ? 0 : (uint64_t)(a % v);
                     break; }
        case 0x82: { uint64_t v = st[--sp];
                     if (!v) trap(E, WX_TRAP_DIV_ZERO);
                     st[sp-1] %= v; break; }
        case 0x83: { uint64_t v = st[--sp]; st[sp-1] &= v; break; }
        case 0x84: { uint64_t v = st[--sp]; st[sp-1] |= v; break; }
        case 0x85: { uint64_t v = st[--sp]; st[sp-1] ^= v; break; }
        case 0x86: { uint64_t v = st[--sp] & 63; st[sp-1] <<= v; break; }
        case 0x87: { uint64_t v = st[--sp] & 63;
                     st[sp-1] = (uint64_t)(s64(st[sp-1]) >> v); break; }
        case 0x88: { uint64_t v = st[--sp] & 63; st[sp-1] >>= v; break; }
        case 0xA7: st[sp-1] &= M32; break;        /* i32.wrap_i64 */
        case 0xAC: st[sp-1] = (uint64_t)(int64_t)s32(st[sp-1]); break;
        case 0xAD: break;                         /* i64.extend_i32_u */
        case 0x00: trap(E, WX_TRAP_UNREACHABLE);
        case 0x01: break;                         /* nop */
        default: trap(E, WX_TRAP_BAD_OP);
        }
    }
func_return:
    /* move the top nres values down to base (results of the function) */
    if (nres)
        memmove(E->vstack + base, E->vstack + sp - nres,
                (size_t)nres * sizeof(uint64_t));
}

/* ---- public API ---------------------------------------------------------- */

Engine *wx_new(const int64_t *ins_flat, int64_t n_ins,
               const int64_t *func_off, int64_t nfuncs,
               const int64_t *func_locals, const int64_t *func_nparams,
               const int64_t *func_nresults, const int64_t *type_nparams,
               const int64_t *type_nresults, const int64_t *imp_nparams,
               const int64_t *imp_nresults, int64_t n_imports,
               const int64_t *br_pool, int64_t /*n_pool*/ n_pool,
               int64_t *table, int64_t ntable, int64_t *globals,
               uint8_t *memory, int64_t *cur_pages, int64_t max_pages,
               HostFn host) {
    (void)n_pool;
    Engine *E = (Engine *)calloc(1, sizeof(Engine));
    if (!E) return NULL;
    /* keep our own copies of the immutable arrays (the Python side frees
     * its temporaries after wx_new) */
    size_t insz = (size_t)n_ins * sizeof(Ins);
    Ins *ins = (Ins *)malloc(insz ? insz : 1);
    memcpy(ins, ins_flat, insz);
#define COPY(name, n)                                                        \
    do {                                                                     \
        size_t sz = (size_t)(n) * sizeof(int64_t);                           \
        int64_t *p = (int64_t *)malloc(sz ? sz : 1);                         \
        memcpy(p, (name), sz);                                               \
        E->name = p;                                                         \
    } while (0)
    E->ins = ins;
    COPY(func_off, nfuncs + 1);
    COPY(func_locals, nfuncs);
    COPY(func_nparams, nfuncs);
    COPY(func_nresults, nfuncs);
    COPY(type_nparams, 1024); /* generous fixed copy; Python pads */
    COPY(type_nresults, 1024);
    COPY(imp_nparams, n_imports ? n_imports : 1);
    COPY(imp_nresults, n_imports ? n_imports : 1);
    COPY(br_pool, n_pool ? n_pool : 1);
#undef COPY
    /* mutable state stays shared with Python */
    E->table = table;
    E->ntable = ntable;
    E->globals = globals;
    E->memory = memory;
    E->cur_pages = cur_pages;
    E->max_pages = max_pages;
    E->n_imports = n_imports;
    E->nfuncs = nfuncs;
    E->host = host;
    /* underflow guard: an unbalanced (hostile) function body can pop at
     * most 3 values per instruction below its base, and every base is
     * >= 0, so a guard band of 3*max_body_len slots below the logical
     * stack keeps ALL underflowing accesses inside this allocation
     * (garbage values, but memory-safe) */
    int64_t max_body = 0;
    for (int64_t f = 0; f < nfuncs; f++) {
        int64_t len = func_off[f + 1] - func_off[f];
        if (len > max_body) max_body = len;
    }
    E->guard = 3 * max_body + 64;
    E->vstack_alloc = (uint64_t *)calloc(
        (size_t)(E->guard + VALUE_STACK_CAP), sizeof(uint64_t));
    E->frames = malloc(FRAME_POOL_CAP * sizeof(Frame));
    if (!E->vstack_alloc || !E->frames) {
        free(E->vstack_alloc); free(E->frames); free(E); return NULL;
    }
    E->vstack = E->vstack_alloc + E->guard;
    return E;
}

void wx_free(Engine *E) {
    if (!E) return;
    free((void *)E->ins);
    free((void *)E->func_off);
    free((void *)E->func_locals);
    free((void *)E->func_nparams);
    free((void *)E->func_nresults);
    free((void *)E->type_nparams);
    free((void *)E->type_nresults);
    free((void *)E->imp_nparams);
    free((void *)E->imp_nresults);
    free((void *)E->br_pool);
    free(E->vstack_alloc);
    free(E->frames);
    free(E);
}

/* call a LOCAL function by global index; args/results via buf. Returns a
 * trap code (WX_OK on success); *nresults is set on success. */
int32_t wx_call(Engine *E, int64_t fi, const uint64_t *args, int32_t nargs,
                uint64_t *results, int32_t *nresults) {
    E->trap_code = WX_OK;
    E->call_depth = 0;
    E->frame_base = 0;
    if (setjmp(E->trap_jmp)) return E->trap_code;
    int64_t lf = fi - E->n_imports;
    if (lf < 0 || lf >= E->nfuncs) return WX_TRAP_BAD_TABLE;
    memcpy(E->vstack, args, (size_t)nargs * sizeof(uint64_t));
    exec_func(E, lf, 0);
    int64_t nr = E->func_nresults[lf];
    memcpy(results, E->vstack, (size_t)nr * sizeof(uint64_t));
    *nresults = (int32_t)nr;
    return WX_OK;
}
